"""ctypes bindings to the paddle_tpu C++ native runtime.

Native pieces (see src/capi.h for the C ABI and the reference files each
mirrors):

- :class:`NativeChannel`   — bounded blocking byte-buffer queue
  (ref: operators/reader/lod_tensor_blocking_queue.h).
- :class:`NativeAllocator` — auto-growth best-fit caching host allocator
  (ref: memory/allocation/auto_growth_best_fit_allocator.cc).
- :class:`MultiSlotDataFeed` — threaded text parser + shuffle + batcher
  (ref: framework/data_feed.cc MultiSlotDataFeed).
- :func:`stat_add` etc.    — global counter registry
  (ref: platform/monitor.h).
"""
from __future__ import annotations

import ctypes
import pickle
from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import build as _build

_lib = None

PTQ_OK = 0
PTQ_CLOSED = -1
PTQ_TIMEOUT = -2
PTQ_ERR = -3

SLOT_FLOAT32 = 0
SLOT_INT64 = 1


def _load():
    global _lib
    if _lib is not None:
        return _lib
    so = _build.build()
    lib = ctypes.CDLL(so)
    i64, i32, u64 = ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64
    u8p = ctypes.POINTER(ctypes.c_uint8)

    lib.ptq_chan_create.restype = i64
    lib.ptq_chan_create.argtypes = [i64]
    lib.ptq_chan_push.restype = i32
    lib.ptq_chan_push.argtypes = [i64, ctypes.c_char_p, i64, i64]
    lib.ptq_chan_pop.restype = i32
    lib.ptq_chan_pop.argtypes = [i64, ctypes.POINTER(u8p),
                                 ctypes.POINTER(i64), i64]
    lib.ptq_chan_close.argtypes = [i64]
    lib.ptq_chan_reopen.argtypes = [i64]
    lib.ptq_chan_size.restype = i64
    lib.ptq_chan_size.argtypes = [i64]
    lib.ptq_chan_destroy.argtypes = [i64]
    lib.ptq_buf_free.argtypes = [u8p]

    lib.ptq_alloc_create.restype = i64
    lib.ptq_alloc_create.argtypes = [i64]
    lib.ptq_alloc_malloc.restype = ctypes.c_void_p
    lib.ptq_alloc_malloc.argtypes = [i64, i64]
    lib.ptq_alloc_free.argtypes = [i64, ctypes.c_void_p]
    lib.ptq_alloc_stats.argtypes = [i64, ctypes.POINTER(i64)]
    lib.ptq_alloc_release_cache.argtypes = [i64]
    lib.ptq_alloc_destroy.argtypes = [i64]

    lib.ptq_feed_create.restype = i64
    lib.ptq_feed_create.argtypes = [i32, ctypes.POINTER(i32), i64, i64]
    lib.ptq_feed_set_files.restype = i32
    lib.ptq_feed_set_files.argtypes = [i64, ctypes.c_char_p]
    lib.ptq_feed_start.restype = i32
    lib.ptq_feed_start.argtypes = [i64, i32, i32, u64, i64]
    lib.ptq_feed_next.restype = i32
    lib.ptq_feed_next.argtypes = [i64, ctypes.POINTER(u8p),
                                  ctypes.POINTER(i64), i64]
    lib.ptq_feed_examples.restype = i64
    lib.ptq_feed_examples.argtypes = [i64]
    lib.ptq_feed_join.argtypes = [i64]
    lib.ptq_feed_destroy.argtypes = [i64]

    lib.ptq_stat_add.argtypes = [ctypes.c_char_p, i64]
    lib.ptq_stat_get.restype = i64
    lib.ptq_stat_get.argtypes = [ctypes.c_char_p]
    lib.ptq_stat_reset.argtypes = [ctypes.c_char_p]
    lib.ptq_stat_names.restype = i64
    lib.ptq_stat_names.argtypes = [ctypes.c_char_p, i64]

    _lib = lib
    return lib


def available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


class Closed(Exception):
    """Channel/feed is closed and drained."""


class Timeout(Exception):
    pass


def _check(rc: int) -> None:
    if rc == PTQ_OK:
        return
    if rc == PTQ_CLOSED:
        raise Closed()
    if rc == PTQ_TIMEOUT:
        raise Timeout()
    raise RuntimeError("native runtime error (rc=%d)" % rc)


class NativeChannel:
    """Bounded blocking queue of python objects (pickled to byte buffers
    on the C++ side). push/pop block; close() drains then raises Closed."""

    def __init__(self, capacity: int = 8):
        self._lib = _load()
        self._h = self._lib.ptq_chan_create(capacity)

    def push(self, obj, timeout_ms: int = -1) -> None:
        data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        _check(self._lib.ptq_chan_push(self._h, data, len(data), timeout_ms))

    def pop(self, timeout_ms: int = -1):
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        _check(self._lib.ptq_chan_pop(self._h, ctypes.byref(out),
                                      ctypes.byref(n), timeout_ms))
        try:
            data = ctypes.string_at(out, n.value)
        finally:
            self._lib.ptq_buf_free(out)
        return pickle.loads(data)

    def close(self) -> None:
        self._lib.ptq_chan_close(self._h)

    def reopen(self) -> None:
        self._lib.ptq_chan_reopen(self._h)

    def __len__(self) -> int:
        return int(self._lib.ptq_chan_size(self._h))

    def __del__(self):
        try:
            self._lib.ptq_chan_destroy(self._h)
        except Exception:
            pass

    def __iter__(self):
        while True:
            try:
                yield self.pop()
            except Closed:
                return


class NativeAllocator:
    """Best-fit caching host allocator; returns numpy views over native
    buffers for zero-copy staging."""

    def __init__(self, alignment: int = 64):
        self._lib = _load()
        self._h = self._lib.ptq_alloc_create(alignment)
        self._live = {}

    def alloc(self, nbytes: int) -> int:
        p = self._lib.ptq_alloc_malloc(self._h, nbytes)
        if not p:
            raise MemoryError(nbytes)
        self._live[p] = nbytes
        return p

    def alloc_array(self, shape, dtype) -> Tuple[int, np.ndarray]:
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        p = self.alloc(max(nbytes, 1))
        buf = (ctypes.c_uint8 * max(nbytes, 1)).from_address(p)
        # The view aliases native memory: pin the allocator (and thereby
        # the block) to the buffer object so GC of `self` can't free the
        # memory under a live view.
        buf._ptq_owner = self
        arr = np.frombuffer(buf, dtype=dtype, count=int(np.prod(shape)))
        return p, arr.reshape(shape)

    def free(self, p: int) -> None:
        self._live.pop(p, None)
        self._lib.ptq_alloc_free(self._h, p)

    def stats(self) -> dict:
        s = (ctypes.c_int64 * 4)()
        self._lib.ptq_alloc_stats(self._h, s)
        return {"bytes_in_use": s[0], "bytes_cached": s[1],
                "n_alloc": s[2], "n_cache_hit": s[3]}

    def release_cache(self) -> None:
        self._lib.ptq_alloc_release_cache(self._h)

    def __del__(self):
        try:
            self._lib.ptq_alloc_destroy(self._h)
        except Exception:
            pass


class MultiSlotDataFeed:
    """Threaded MultiSlot-format text reader.

    ``next_batch()`` returns ``[(array, lod), ...]`` per slot where lod is
    the per-batch cumulative offsets (ref LoD level-0); raises
    :class:`Closed` at end of data.
    """

    def __init__(self, slot_types: Sequence[str], batch_size: int,
                 queue_capacity: int = 16):
        self._lib = _load()
        codes = []
        for t in slot_types:
            if t in ("float32", "float", SLOT_FLOAT32):
                codes.append(SLOT_FLOAT32)
            elif t in ("int64", "int", SLOT_INT64):
                codes.append(SLOT_INT64)
            else:
                raise ValueError("unsupported slot type %r" % (t,))
        arr = (ctypes.c_int32 * len(codes))(*codes)
        self._h = self._lib.ptq_feed_create(len(codes), arr, batch_size,
                                            queue_capacity)
        if self._h < 0:
            raise ValueError("bad feed config")
        self._n_slots = len(codes)

    def set_filelist(self, files: Sequence[str]) -> None:
        joined = "\n".join(files).encode()
        _check(self._lib.ptq_feed_set_files(self._h, joined))

    def start(self, n_threads: int = 1, shuffle: bool = False,
              seed: int = 0, buffer_size: int = 1024) -> None:
        _check(self._lib.ptq_feed_start(self._h, n_threads,
                                        1 if shuffle else 0, seed,
                                        buffer_size))

    def next_batch(self, timeout_ms: int = -1) -> List[Tuple[np.ndarray,
                                                             np.ndarray]]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        _check(self._lib.ptq_feed_next(self._h, ctypes.byref(out),
                                       ctypes.byref(n), timeout_ms))
        try:
            data = ctypes.string_at(out, n.value)
        finally:
            self._lib.ptq_buf_free(out)
        return self._decode(data)

    def _decode(self, data: bytes):
        off = 0

        def rd_i64():
            nonlocal off
            v = int(np.frombuffer(data, "<i8", 1, off)[0])
            off += 8
            return v

        n_slots = rd_i64()
        assert n_slots == self._n_slots, (n_slots, self._n_slots)
        slots = []
        for _ in range(n_slots):
            t = int(np.frombuffer(data, "<i4", 1, off)[0])
            off += 4
            n_lod = rd_i64()
            lod = np.frombuffer(data, "<i8", n_lod, off).copy()
            off += 8 * n_lod
            n_vals = rd_i64()
            dt = "<f4" if t == SLOT_FLOAT32 else "<i8"
            vals = np.frombuffer(data, dt, n_vals, off).copy()
            off += n_vals * np.dtype(dt).itemsize
            slots.append((vals, lod))
        return slots

    def examples_parsed(self) -> int:
        return int(self._lib.ptq_feed_examples(self._h))

    def join(self) -> None:
        self._lib.ptq_feed_join(self._h)

    def __iter__(self):
        while True:
            try:
                yield self.next_batch()
            except Closed:
                return

    def __del__(self):
        try:
            self._lib.ptq_feed_destroy(self._h)
        except Exception:
            pass


def stat_add(name: str, delta: int = 1) -> None:
    _load().ptq_stat_add(name.encode(), delta)


def stat_get(name: str) -> int:
    return int(_load().ptq_stat_get(name.encode()))


def stat_reset(name: str) -> None:
    _load().ptq_stat_reset(name.encode())


def stat_names() -> List[str]:
    lib = _load()
    n = lib.ptq_stat_names(None, 0)
    buf = ctypes.create_string_buffer(int(n) + 1)
    lib.ptq_stat_names(buf, n + 1)
    s = buf.value.decode()
    return s.split("\n") if s else []


# ---------------------------------------------------------------------------
# Profiler trace events (src/trace_events.cc; ref: platform/profiler.h +
# tools/timeline.py) — native ring store + chrome-trace writer.
# ---------------------------------------------------------------------------

def _trace_lib():
    lib = _load()
    if not hasattr(lib, "_trace_bound"):
        i32, i64 = ctypes.c_int32, ctypes.c_int64
        lib.ptq_trace_enable.argtypes = [ctypes.c_int]
        lib.ptq_trace_name_id.restype = i32
        lib.ptq_trace_name_id.argtypes = [ctypes.c_char_p]
        lib.ptq_trace_record.argtypes = [i32, i32, i64, i64]
        lib.ptq_trace_count.restype = i64
        lib.ptq_trace_dropped.restype = i64
        lib.ptq_trace_export.restype = ctypes.c_int
        lib.ptq_trace_export.argtypes = [ctypes.c_char_p,
                                         ctypes.c_char_p]
        lib.ptq_trace_stats.restype = i32
        lib.ptq_trace_stats.argtypes = [ctypes.POINTER(i64),
                                        ctypes.POINTER(i64),
                                        ctypes.POINTER(i64), i32]
        lib.ptq_trace_name_at.restype = ctypes.c_char_p
        lib.ptq_trace_name_at.argtypes = [i32]
        lib._trace_bound = True
    return lib


class NativeTrace:
    """Event store + chrome-trace exporter backed by the C++ runtime."""

    @staticmethod
    def enable(on=True):
        _trace_lib().ptq_trace_enable(1 if on else 0)

    @staticmethod
    def name_id(name: str) -> int:
        return _trace_lib().ptq_trace_name_id(name.encode())

    @staticmethod
    def record(name_id: int, tid: int, start_us: int, dur_us: int):
        _trace_lib().ptq_trace_record(name_id, tid, start_us, dur_us)

    @staticmethod
    def count() -> int:
        return _trace_lib().ptq_trace_count()

    @staticmethod
    def dropped() -> int:
        """Events discarded beyond the store cap (truncated trace)."""
        return _trace_lib().ptq_trace_dropped()

    @staticmethod
    def reset():
        _trace_lib().ptq_trace_reset()

    @staticmethod
    def export(path: str, process_name="paddle_tpu") -> int:
        lib = _trace_lib()
        if lib.ptq_trace_dropped() > 0:
            import warnings

            warnings.warn(
                "trace store overflowed: %d events were dropped; the "
                "exported trace is truncated"
                % lib.ptq_trace_dropped())
        return lib.ptq_trace_export(path.encode(), process_name.encode())

    @staticmethod
    def stats():
        lib = _trace_lib()
        n = lib.ptq_trace_stats(None, None, None, 0)
        if n == 0:
            return {}
        i64 = ctypes.c_int64
        counts = (i64 * n)()
        totals = (i64 * n)()
        maxes = (i64 * n)()
        lib.ptq_trace_stats(counts, totals, maxes, n)
        out = {}
        for i in range(n):
            name = lib.ptq_trace_name_at(i).decode()
            out[name] = {"count": counts[i], "total_us": totals[i],
                         "max_us": maxes[i]}
        return out


# ---------------------------------------------------------------------------
# Ragged <-> padded batching (src/ragged.cc; ref:
# operators/math/sequence_padding.cc).
# ---------------------------------------------------------------------------

def _ragged_lib():
    lib = _load()
    if not hasattr(lib, "_ragged_bound"):
        i64 = ctypes.c_int64
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.ptq_ragged_pad.restype = i64
        lib.ptq_ragged_pad.argtypes = [u8p, ctypes.POINTER(i64), i64,
                                       i64, i64, i64, u8p]
        lib.ptq_ragged_unpad.restype = i64
        lib.ptq_ragged_unpad.argtypes = [u8p, ctypes.POINTER(i64), i64,
                                         i64, i64, i64, u8p]
        lib.ptq_lod_to_lengths.argtypes = [ctypes.POINTER(i64), i64,
                                           ctypes.POINTER(i64)]
        lib._ragged_bound = True
    return lib


def _u8view(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def ragged_pad(values: np.ndarray, lengths, max_len=None):
    """Concatenated rows [total, width...] + per-item lengths ->
    padded [batch, max_len, width...] (zero pad), via the native
    single-memcpy-per-row kernel."""
    lib = _ragged_lib()
    values = np.ascontiguousarray(values)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    if lengths.size and int(lengths.sum()) > values.shape[0]:
        raise ValueError(
            "ragged_pad: sum(lengths)=%d exceeds the %d rows in values"
            % (int(lengths.sum()), values.shape[0]))
    if lengths.size and int(lengths.min()) < 0:
        raise ValueError("ragged_pad: negative length")
    batch = len(lengths)
    max_len = int(max_len if max_len is not None
                  else (lengths.max() if batch else 0))
    width_shape = values.shape[1:]
    width = int(np.prod(width_shape)) if width_shape else 1
    out = np.empty((batch, max_len) + tuple(width_shape), values.dtype)
    lib.ptq_ragged_pad(
        _u8view(values), lengths.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64)),
        batch, max_len, width, values.dtype.itemsize, _u8view(out))
    return out


def ragged_unpad(padded: np.ndarray, lengths):
    """Inverse of ragged_pad: padded [batch, max_len, width...] ->
    concatenated [sum(min(len, max_len)), width...]."""
    lib = _ragged_lib()
    padded = np.ascontiguousarray(padded)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    if len(lengths) != padded.shape[0]:
        raise ValueError(
            "ragged_unpad: %d lengths for %d batch items"
            % (len(lengths), padded.shape[0]))
    if lengths.size and int(lengths.min()) < 0:
        raise ValueError("ragged_unpad: negative length")
    batch, max_len = padded.shape[0], padded.shape[1]
    width_shape = padded.shape[2:]
    width = int(np.prod(width_shape)) if width_shape else 1
    total = int(np.minimum(lengths, max_len).sum())
    out = np.empty((total,) + tuple(width_shape), padded.dtype)
    lib.ptq_ragged_unpad(
        _u8view(padded), lengths.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64)),
        batch, max_len, width, padded.dtype.itemsize, _u8view(out))
    return out


def lod_to_lengths(lod):
    """Level-0 LoD offsets [0, n1, n1+n2, ...] -> per-item lengths."""
    lib = _ragged_lib()
    lod = np.ascontiguousarray(lod, dtype=np.int64)
    batch = len(lod) - 1
    out = np.empty((batch,), np.int64)
    lib.ptq_lod_to_lengths(
        lod.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), batch,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return out


# ---- model-file encryption (crypto.cc; ref: framework/io/crypto/
# aes_cipher.h:48, cipher.h:24, bound in pybind/crypto.cc) ----

def _crypto_lib():
    lib = _load()
    if not hasattr(lib, "_crypto_ready"):
        i64 = ctypes.c_int64
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.ptq_crypto_gen_key.restype = ctypes.c_int
        lib.ptq_crypto_gen_key.argtypes = [u8p, i64]
        for fn in (lib.ptq_crypto_encrypt, lib.ptq_crypto_decrypt):
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_char_p, i64, ctypes.c_char_p, i64,
                           ctypes.POINTER(u8p), ctypes.POINTER(i64)]
        lib.ptq_crypto_selftest.restype = ctypes.c_int
        lib.ptq_crypto_selftest.argtypes = []
        lib._crypto_ready = True
    return lib


def crypto_selftest() -> bool:
    """FIPS-197 C.3 / FIPS-180-4 B.1 known-answer self-check."""
    return _crypto_lib().ptq_crypto_selftest() == 0


def crypto_gen_key(length: int = 32) -> bytes:
    lib = _crypto_lib()
    buf = (ctypes.c_uint8 * length)()
    if lib.ptq_crypto_gen_key(buf, length) != PTQ_OK:
        raise RuntimeError("key generation failed")
    return bytes(buf)


def _crypto_call(fn, key: bytes, data: bytes) -> bytes:
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_int64()
    rc = fn(key, len(key), data, len(data),
            ctypes.byref(out), ctypes.byref(out_len))
    if rc == -1:
        raise ValueError(
            "decryption failed: wrong key or corrupted ciphertext")
    if rc != PTQ_OK:
        raise RuntimeError("crypto operation failed (rc=%d)" % rc)
    try:
        return ctypes.string_at(out, out_len.value)
    finally:
        _crypto_lib().ptq_buf_free(out)


def crypto_encrypt(key: bytes, plaintext: bytes) -> bytes:
    """Seals plaintext: AES-256-CTR + HMAC-SHA256 encrypt-then-MAC."""
    return _crypto_call(_crypto_lib().ptq_crypto_encrypt, key, plaintext)


def crypto_decrypt(key: bytes, sealed: bytes) -> bytes:
    """Opens a sealed buffer; raises ValueError on tag mismatch."""
    return _crypto_call(_crypto_lib().ptq_crypto_decrypt, key, sealed)
