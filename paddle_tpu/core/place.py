"""Device identity ("Place") for the TPU-native framework.

Reference parity: `paddle/fluid/platform/place.h:26-98` models CPUPlace /
CUDAPlace / CUDAPinnedPlace as a boost::variant. Here a Place maps onto a JAX
device; `TPUPlace` is first-class (the north star adds it next to CPUPlace and
CUDAPlace). `CUDAPlace` is kept as an API alias that resolves to the best
accelerator present so reference scripts run unmodified.
"""
from __future__ import annotations

import functools


class Place:
    """Base device identity. Resolves lazily to a concrete `jax.Device`."""

    _kind = "undefined"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    # -- identity ---------------------------------------------------------
    def get_device_id(self) -> int:
        return self._device_id

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self._kind == other._kind
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self._kind, self._device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self._device_id)

    # -- resolution -------------------------------------------------------
    def jax_device(self):
        """Return the concrete jax.Device this place denotes."""
        import jax

        devs = _devices_of_kind(self._kind)
        if not devs:
            # Graceful fallback (e.g. TPUPlace on a CPU-only CI host).
            devs = jax.devices()
        return devs[self._device_id % len(devs)]

    def is_cpu_place(self):
        return self._kind == "cpu"

    def is_gpu_place(self):
        return self._kind == "accel"

    def is_tpu_place(self):
        return self._kind == "accel"


@functools.lru_cache(maxsize=None)
def _devices_of_kind(kind: str):
    import jax

    if kind == "cpu":
        try:
            return tuple(jax.devices("cpu"))
        except RuntimeError:
            return tuple(jax.devices())
    # "accel": whatever accelerator backend is the default (tpu under libtpu,
    # the axon tunnel in this environment, cpu otherwise).
    return tuple(jax.devices())


class CPUPlace(Place):
    _kind = "cpu"

    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    """First-class TPU device identity (north star: paddle.TPUPlace)."""

    _kind = "accel"


class CUDAPlace(Place):
    """API-compat alias: resolves to the accelerator backend (TPU here)."""

    _kind = "accel"


class CUDAPinnedPlace(Place):
    _kind = "cpu"

    def __init__(self):
        super().__init__(0)


class XPUPlace(Place):
    _kind = "accel"


def _current_expected_place():
    """Default place: the accelerator if one exists, else CPU."""
    import jax

    try:
        plat = jax.default_backend()
    except Exception:
        plat = "cpu"
    if plat == "cpu":
        return CPUPlace()
    return TPUPlace(0)
