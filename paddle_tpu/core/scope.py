"""Scope: hierarchical name -> value symbol table.

Reference parity: `paddle/fluid/framework/scope.h:46` / `variable.h:26`.
Values here are jax Arrays resident on device HBM (persistables: parameters,
optimizer state, running stats) plus host-side metadata (LoD info).
"""
from __future__ import annotations

from typing import Dict, Optional


class Scope:
    _uid_counter = 0

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, object] = {}
        self._parent = parent
        self._kids = []
        Scope._uid_counter += 1
        self._uid = Scope._uid_counter  # never-reused compile-cache id

    def var(self, name: str):
        """Find-or-declare (reference: Scope::Var)."""
        if name not in self._vars:
            self._vars[name] = None
        return name

    def find_var(self, name: str):
        if name in self._vars:
            return self._vars[name]
        if self._parent is not None:
            return self._parent.find_var(name)
        return None

    def has_var(self, name: str) -> bool:
        if name in self._vars:
            return True
        return self._parent.has_var(name) if self._parent else False

    def set_var(self, name: str, value):
        self._vars[name] = value

    def erase(self, name: str):
        self._vars.pop(name, None)

    def local_var_names(self):
        return list(self._vars)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


class _ScopeGuard:
    def __init__(self, scope):
        self._scope = scope

    def __enter__(self):
        global _global_scope
        self._old = _global_scope
        _global_scope = self._scope

    def __exit__(self, *a):
        global _global_scope
        _global_scope = self._old


def scope_guard(scope: Scope):
    return _ScopeGuard(scope)
