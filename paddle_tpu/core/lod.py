"""Multi-level LoD (level-of-detail / nested ragged sequences).

Reference parity: `paddle/fluid/framework/lod_tensor.h:52` (offset-based
LoD over a dense tensor, arbitrarily nested: e.g. a 2-level LoD models
paragraphs -> sentences -> words) and the python surface
`python/paddle/fluid/lod_tensor.py` (create_lod_tensor /
create_random_int_lodtensor, length-based <-> offset-based conversion).

TPU-native design: XLA computations take STATIC shapes, so the ragged
structure lives HOST-SIDE next to a dense row-major payload (exactly the
reference's memory layout — LoD never touches the kernels there either).
`to_padded()` bridges to the padded+length layout the sequence ops
consume on device; `from_padded()` comes back. The nesting itself is
pure metadata, so arbitrary depth costs nothing."""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = [
    "LoDTensor", "create_lod_tensor", "create_random_int_lodtensor",
]


def _lens_to_offsets(lens: Sequence[int]) -> List[int]:
    out = [0]
    for n in lens:
        out.append(out[-1] + int(n))
    return out


def _offsets_to_lens(offsets: Sequence[int]) -> List[int]:
    return [int(offsets[i + 1] - offsets[i])
            for i in range(len(offsets) - 1)]


class LoDTensor:
    """Dense payload + offset-based multi-level LoD.

    lod() returns the OFFSET form (reference LoDTensor::lod):
    lod()[i] partitions the entries of level i+1 (or the payload rows
    for the innermost level). recursive_sequence_lengths() is the
    LENGTH form users build (reference: set_recursive_sequence_lengths).
    """

    def __init__(self, data=None, lod: Optional[List[List[int]]] = None):
        self._data = None if data is None else np.asarray(data)
        self._lod: List[List[int]] = [list(map(int, lv))
                                      for lv in (lod or [])]

    # -- payload -----------------------------------------------------------
    def set(self, data, place=None):
        self._data = np.asarray(data)

    def numpy(self):
        return self._data

    def __array__(self, dtype=None):
        a = self._data
        return a.astype(dtype) if dtype is not None else a

    def shape(self):
        return list(self._data.shape) if self._data is not None else []

    # -- LoD metadata ------------------------------------------------------
    def lod(self) -> List[List[int]]:
        return [list(lv) for lv in self._lod]

    def set_lod(self, lod: List[List[int]]):
        self._lod = [list(map(int, lv)) for lv in lod]

    def recursive_sequence_lengths(self) -> List[List[int]]:
        return [_offsets_to_lens(lv) for lv in self._lod]

    def set_recursive_sequence_lengths(self, lens: List[List[int]]):
        self._lod = [_lens_to_offsets(lv) for lv in lens]

    def lod_level(self) -> int:
        return len(self._lod)

    def has_valid_recursive_sequence_lengths(self) -> bool:
        """Reference CheckLoD (lod_tensor.cc): every level's offsets are
        non-decreasing from 0; level i's last offset equals the number
        of entries of level i+1; the innermost level's last offset
        equals the payload's first dimension."""
        if self._data is None:
            return False
        for i, lv in enumerate(self._lod):
            if not lv or lv[0] != 0:
                return False
            if any(lv[j] > lv[j + 1] for j in range(len(lv) - 1)):
                return False
            end = (len(self._lod[i + 1]) - 1 if i + 1 < len(self._lod)
                   else int(self._data.shape[0]))
            if lv[-1] != end:
                return False
        return True

    # -- bridges to the device-side padded layout -------------------------
    def innermost_lengths(self) -> List[int]:
        """Sequence lengths at the finest granularity (rows per leaf
        sequence)."""
        if not self._lod:
            return [int(self._data.shape[0])]
        return _offsets_to_lens(self._lod[-1])

    def to_padded(self, pad_value=0.0):
        """(padded [n_seq, max_len, ...], lengths int64 [n_seq]): the
        static-shape layout the sequence ops take on device."""
        lens = self.innermost_lengths()
        offsets = _lens_to_offsets(lens)
        max_len = max(lens) if lens else 0
        feat = self._data.shape[1:]
        out = np.full((len(lens), max_len) + feat, pad_value,
                      self._data.dtype)
        for i, n in enumerate(lens):
            out[i, :n] = self._data[offsets[i]:offsets[i] + n]
        return out, np.asarray(lens, np.int64)

    @staticmethod
    def from_padded(padded, lengths, outer_lens=None):
        """Inverse of to_padded; optional outer_lens adds a second LoD
        level (how many inner sequences each outer sequence owns)."""
        padded = np.asarray(padded)
        lengths = [int(x) for x in np.asarray(lengths).reshape(-1)]
        rows = [padded[i, :n] for i, n in enumerate(lengths)]
        data = (np.concatenate(rows, axis=0) if rows
                else padded[:0].reshape((0,) + padded.shape[2:]))
        lod = [_lens_to_offsets(lengths)]
        if outer_lens is not None:
            lod.insert(0, _lens_to_offsets(
                [int(x) for x in outer_lens]))
        return LoDTensor(data, lod)

    def __repr__(self):
        return "LoDTensor(shape=%s, lod=%s)" % (self.shape(), self._lod)


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Reference: lod_tensor.py create_lod_tensor — numpy array, list of
    sequences (single-level, like the reference's DataToLoDTensorConverter
    path), or LoDTensor + LENGTH-based LoD -> LoDTensor with offset LoD."""
    if isinstance(data, LoDTensor):
        return create_lod_tensor(data.numpy(), recursive_seq_lens, place)
    if isinstance(data, list):
        # reference contract: the top list is the batch of sequences and
        # must match recursive_seq_lens exactly (lod_tensor.py:137)
        lens = [len(seq) for seq in data]
        if [lens] != [list(map(int, lv)) for lv in recursive_seq_lens]:
            raise AssertionError(
                "data and recursive_seq_lens do not match")
        flat = [np.asarray(x).reshape(-1) for seq in data for x in seq]
        t = LoDTensor(np.stack(flat) if flat else np.zeros((0, 1)))
    else:
        t = LoDTensor(np.asarray(data))
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    if not t.has_valid_recursive_sequence_lengths():
        raise AssertionError(
            "the provided recursive_seq_lens info is invalid for the "
            "data (innermost total %r vs payload rows %r)"
            % (recursive_seq_lens, t.shape()))
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=10, seed=None):
    """Reference: lod_tensor.py create_random_int_lodtensor — payload
    rows = sum of the innermost lengths, feature dims = base_shape."""
    total = sum(int(x) for x in recursive_seq_lens[-1])
    r = np.random.RandomState(seed)
    data = r.randint(low, high + 1,
                     (total,) + tuple(base_shape)).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
