"""Dtype system: VarType enum names <-> numpy/jax dtypes.

Reference parity: `paddle/fluid/framework/framework.proto:104-162` (VarType),
`paddle/fluid/platform/float16.h` (software fp16). On TPU, bfloat16 is the
native 16-bit type (MXU-friendly); fp16 is kept for API compatibility.
"""
from __future__ import annotations

import numpy as np

try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = np.dtype("float32")

# Canonical string names used throughout the framework.
_STR_TO_NP = {
    "bool": np.dtype("bool"),
    "int8": np.dtype("int8"),
    "uint8": np.dtype("uint8"),
    "int16": np.dtype("int16"),
    "int32": np.dtype("int32"),
    "int64": np.dtype("int64"),
    "float16": np.dtype("float16"),
    "bfloat16": _BF16,
    "float32": np.dtype("float32"),
    "float64": np.dtype("float64"),
    "complex64": np.dtype("complex64"),
    "complex128": np.dtype("complex128"),
}

# Reference framework.proto VarType.Type integer codes (framework.proto:106-131)
# kept so serialized programs stay interchangeable.
_STR_TO_PROTO = {
    "bool": 0,
    "int16": 1,
    "int32": 2,
    "int64": 3,
    "float16": 4,
    "float32": 5,
    "float64": 6,
    "int8": 21,
    "uint8": 20,
    "bfloat16": 22,
    "complex64": 23,
    "complex128": 24,
}
_PROTO_TO_STR = {v: k for k, v in _STR_TO_PROTO.items()}


class VarDesc:
    class VarType:
        BOOL = 0
        INT16 = 1
        INT32 = 2
        INT64 = 3
        FP16 = 4
        FP32 = 5
        FP64 = 6
        UINT8 = 20
        INT8 = 21
        BF16 = 22
        COMPLEX64 = 23
        COMPLEX128 = 24
        # container kinds
        LOD_TENSOR = 7
        SELECTED_ROWS = 8
        FEED_MINIBATCH = 9
        FETCH_LIST = 10
        STEP_SCOPES = 11
        LOD_RANK_TABLE = 12
        LOD_TENSOR_ARRAY = 13
        RAW = 17


def normalize_dtype(dtype) -> str:
    """Accept str / numpy dtype / jax dtype / VarType int -> canonical str."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        s = {"float": "float32", "double": "float64", "int": "int32",
             "half": "float16", "long": "int64"}.get(dtype, dtype)
        if s not in _STR_TO_NP:
            raise ValueError("unknown dtype %r" % (dtype,))
        return s
    if isinstance(dtype, int):
        return _PROTO_TO_STR[dtype]
    npdt = np.dtype(dtype)
    if npdt == _BF16:
        return "bfloat16"
    name = npdt.name
    if name not in _STR_TO_NP:
        raise ValueError("unsupported dtype %r" % (dtype,))
    return name


def to_numpy_dtype(dtype) -> np.dtype:
    return _STR_TO_NP[normalize_dtype(dtype)]


def to_proto(dtype) -> int:
    return _STR_TO_PROTO[normalize_dtype(dtype)]


def is_floating(dtype) -> bool:
    return normalize_dtype(dtype) in (
        "float16", "bfloat16", "float32", "float64")
