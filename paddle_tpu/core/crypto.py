"""Model-file encryption API.

Mirrors the reference's crypto surface (cipher classes
``paddle/fluid/framework/io/crypto/cipher.h:24`` /
``cipher_utils.h:23``, python-bound in ``pybind/crypto.cc``): a
``Cipher`` with Encrypt/Decrypt on strings and files, a
``CipherFactory`` selecting the cipher from a config file, and
``CipherUtils`` for key management. The primitive underneath is the
native ``crypto.cc`` sealed format (AES-256-CTR + HMAC-SHA256
encrypt-then-MAC) rather than the reference's Crypto++ AES-GCM — same
confidentiality+integrity contract, zero external dependencies.

Config files use the reference's ``key: value`` per-line shape, e.g.::

    cipher_name: AES_CTR_EtM(256)
"""
from __future__ import annotations

import os

from . import native as _native


class Cipher:
    """Authenticated symmetric cipher over bytes and files."""

    def encrypt(self, plaintext, key):
        return _native.crypto_encrypt(_as_bytes(key), _as_bytes(plaintext))

    def decrypt(self, ciphertext, key):
        """Raises ValueError on wrong key or corrupted data."""
        return _native.crypto_decrypt(_as_bytes(key), _as_bytes(ciphertext))

    def encrypt_to_file(self, plaintext, key, filename):
        data = self.encrypt(plaintext, key)
        tmp = filename + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, filename)

    def decrypt_from_file(self, key, filename):
        with open(filename, "rb") as f:
            return self.decrypt(f.read(), key)


class AESCipher(Cipher):
    """Named alias kept for parity with the reference's AESCipher
    (aes_cipher.h:48)."""


class CipherUtils:
    """Key management helpers (reference cipher_utils.h:23)."""

    AES_DEFAULT_IV_SIZE = 128   # bits
    AES_DEFAULT_TAG_SIZE = 256  # bits: the sealed format's HMAC-SHA256

    @staticmethod
    def gen_key(length):
        """Random key of `length` bits (the reference API takes bits)."""
        if length % 8:
            raise ValueError("key length must be a multiple of 8 bits")
        return _native.crypto_gen_key(length // 8)

    @staticmethod
    def gen_key_to_file(length, filename):
        key = CipherUtils.gen_key(length)
        tmp = filename + ".tmp"
        # owner-only from the first byte: a default-umask open would
        # leave the secret world-readable until (or past) a chmod
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            os.write(fd, key)
        finally:
            os.close(fd)
        os.replace(tmp, filename)
        return key

    @staticmethod
    def read_key_from_file(filename):
        with open(filename, "rb") as f:
            return f.read()

    @staticmethod
    def load_config(config_file):
        """`key: value` per line; '#' comments and blank lines skipped."""
        out = {}
        with open(config_file) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or ":" not in line:
                    continue
                k, v = line.split(":", 1)
                out[k.strip()] = v.strip()
        return out


class CipherFactory:
    """Creates a cipher from an optional config file (cipher.h:44)."""

    _KNOWN = ("AES_CTR_EtM(256)", "AES_CTR_NoPadding(256)", "")

    @staticmethod
    def create_cipher(config_file=None):
        if config_file:
            cfg = CipherUtils.load_config(config_file)
            name = cfg.get("cipher_name", "")
            if name not in CipherFactory._KNOWN:
                raise ValueError(
                    "unsupported cipher_name %r (supported: %s)"
                    % (name, ", ".join(n for n in CipherFactory._KNOWN
                                       if n)))
        return AESCipher()


def _as_bytes(v):
    if isinstance(v, bytes):
        return v
    if isinstance(v, bytearray):
        return bytes(v)
    if isinstance(v, str):
        return v.encode("utf-8")
    raise TypeError("expected bytes or str, got %s" % type(v).__name__)
