"""paddle_tpu.core — device/dtype/scope primitives (reference: the pybind
`core` module, `paddle/fluid/pybind/pybind.cc:321`)."""
from .place import (  # noqa: F401
    Place, CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace, XPUPlace,
)
from .scope import Scope, global_scope, scope_guard  # noqa: F401
from .types import VarDesc, normalize_dtype, to_numpy_dtype  # noqa: F401
from .crypto import (  # noqa: F401
    AESCipher, Cipher, CipherFactory, CipherUtils,
)
