"""Memory facade + stats surface.

Reference parity: `paddle/fluid/memory/malloc.h:32-37` (memory::Alloc /
AllocShared) and `memory/allocation/allocator_facade.h:32` with the
gflags-selectable strategies, plus the STAT registry GPU-memory gauges
(`platform/monitor.h`). TPU-native split: HBM allocation belongs to
PJRT/XLA (buffer donation + arena planning beat any hand allocator —
SURVEY.md §2 row "Memory"); this facade exposes the reference-shaped
API over (a) the native best-fit HOST allocator
(core/native/src/allocator.cc) for pinned staging buffers and (b) the
per-device PJRT memory statistics."""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .place import CPUPlace, TPUPlace


class Allocation:
    """Handle returned by Alloc (reference: memory::Allocation)."""

    __slots__ = ("ptr", "size", "place", "_buf")

    def __init__(self, ptr, size, place, buf=None):
        self.ptr = ptr
        self.size = size
        self.place = place
        self._buf = buf


def Alloc(place, size: int) -> Allocation:
    """memory::Alloc (reference: malloc.h:32). Host places use the
    native caching allocator when built; device places raise — HBM
    buffers are created by XLA, not by user code."""
    name = type(place).__name__
    if isinstance(place, TPUPlace) or (
            name.startswith(("CUDA", "XPU")) and "Pinned" not in name):
        from .errors import UnavailableError

        raise UnavailableError(
            "device HBM is managed by PJRT/XLA (donated buffers, arena "
            "planning); allocate through tensors, not memory.Alloc")
    try:
        alloc = _host_allocator()
        ptr = alloc.alloc(max(int(size), 1))
        return Allocation(ptr, int(size), place)
    except Exception:
        buf = np.empty((max(int(size), 1),), np.uint8)
        return Allocation(buf.ctypes.data, int(size), place, buf=buf)


_HOST_ALLOCATOR = None


def _host_allocator():
    global _HOST_ALLOCATOR
    if _HOST_ALLOCATOR is None:
        from .native import NativeAllocator

        _HOST_ALLOCATOR = NativeAllocator()
    return _HOST_ALLOCATOR


def Free(allocation: Allocation):
    if allocation._buf is not None:
        allocation._buf = None
        return
    try:
        _host_allocator().free(allocation.ptr)
    except Exception:
        pass


def memory_stats(device=None) -> Dict[str, int]:
    """Per-device memory statistics via PJRT (reference: the
    STAT_ADD/gpu_mem monitor gauges, platform/monitor.h)."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    stats = {}
    try:
        raw = dev.memory_stats() or {}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                  "largest_alloc_size", "num_allocs"):
            if k in raw:
                stats[k] = int(raw[k])
    except Exception:
        pass
    return stats


def max_memory_allocated(device=None) -> int:
    return memory_stats(device).get("peak_bytes_in_use", 0)


def memory_allocated(device=None) -> int:
    return memory_stats(device).get("bytes_in_use", 0)
