"""SelectedRows: sparse row-set gradient container.

Reference parity: `paddle/fluid/framework/selected_rows.h` — the (rows,
value, height) triple produced by embedding backward and consumed by the
optimizers' sparse kernels (`operators/optimizers/adam_op.h` sparse path,
`sgd_op.h` SelectedRows branch).

TPU-native placement: INSIDE a jitted XLA computation, dense scatter-add
fused by XLA is the optimal embedding-gradient form (MXU/HBM work is the
same and there is no host round-trip), so the static lowering keeps dense
grads. SelectedRows exists for the tiers where sparsity pays on HOSTS:
the eager (dygraph) engine (is_sparse=True embeddings avoid densifying a
vocab-sized grad per microstep) and the parameter-server tier (push only
the touched rows over DCN, `distributed/ps.py` sparse_grad_sgd)."""
from __future__ import annotations

from typing import Optional

import numpy as np


class SelectedRows:
    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height: int):
        self.rows = rows          # int array [k]
        self.values = values      # [k, ...] row payloads
        self.height = int(height)  # dense dim-0 extent

    # -- framework duck-typing --------------------------------------------
    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def __repr__(self):
        return "SelectedRows(rows=%d, height=%d, dim=%s)" % (
            len(np.asarray(self.rows)), self.height,
            tuple(self.values.shape[1:]))

    # -- algebra -----------------------------------------------------------
    def merge(self) -> "SelectedRows":
        """Deduplicate rows via segment-sum (reference:
        operators/math/selected_rows_functor.cc MergeAdd)."""
        import jax
        import jax.numpy as jnp

        rows = jnp.asarray(self.rows)
        uniq, inv = jnp.unique(rows, return_inverse=True,
                               size=rows.shape[0], fill_value=-1)
        summed = jax.ops.segment_sum(jnp.asarray(self.values),
                                     inv.reshape(-1),
                                     num_segments=rows.shape[0])
        keep = uniq >= 0
        # keep static shapes: invalid slots get row -1 with zero values
        summed = jnp.where(keep.reshape((-1,) + (1,) *
                                        (summed.ndim - 1)), summed, 0)
        return SelectedRows(uniq, summed, self.height)

    def to_dense(self):
        import jax.numpy as jnp

        dense = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                          jnp.asarray(self.values).dtype)
        rows = jnp.asarray(self.rows)
        valid = rows >= 0
        safe_rows = jnp.where(valid, rows, 0)
        vals = jnp.where(valid.reshape((-1,) + (1,) *
                                       (self.values.ndim - 1)),
                         jnp.asarray(self.values), 0)
        return dense.at[safe_rows].add(vals)

    def __add__(self, other):
        import jax.numpy as jnp

        if isinstance(other, SelectedRows):
            assert other.height == self.height, (other.height, self.height)
            return SelectedRows(
                jnp.concatenate([jnp.asarray(self.rows),
                                 jnp.asarray(other.rows)]),
                jnp.concatenate([jnp.asarray(self.values),
                                 jnp.asarray(other.values)]),
                self.height)
        if other is None or (np.isscalar(other) and other == 0):
            return self
        return self.to_dense() + other

    __radd__ = __add__


def sr_add(a, b):
    """acc-aware add where either side may be SelectedRows or dense."""
    if isinstance(a, SelectedRows) or isinstance(b, SelectedRows):
        if isinstance(a, SelectedRows):
            return a + b
        return b + a
    return a + b
