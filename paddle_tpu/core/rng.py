"""PRNG key construction for the whole framework.

The reference seeds a per-device curand generator state
(`paddle/fluid/operators/dropout_op.cu`, `uniform_random_op.cc`); the
TPU-native design threads counter-based stateless keys instead
(deterministic given program.random_seed + op index). This module picks
the key *implementation*: threefry2x32 is JAX's portable default but
generates bits with long serial VPU ops — on a BERT-base step the
dropout masks alone are ~1.2G draws while the MXU idles. XLA's
RngBitGenerator ("rbg") uses the hardware RNG path on TPU. Controlled by
FLAGS_prng_impl ("auto" = rbg on TPU, threefry on CPU so seeded CPU
tests keep their exact streams).

`fold_in`/`split`/`bernoulli`/`uniform`/`normal` all accept the typed
keys `make_key` returns, so consumers are impl-agnostic.
"""
from __future__ import annotations

import jax

from ..utils.flags import get_flag


def resolved_impl() -> str:
    """The concrete key impl the current flag + backend resolve to."""
    impl = str(get_flag("FLAGS_prng_impl", "auto"))
    if impl == "auto":
        return "rbg" if jax.default_backend() == "tpu" else "threefry2x32"
    return impl


def make_key(seed):
    """A typed PRNG key for `seed` under the configured implementation.

    Works with a traced (dynamic) seed — used inside the jitted train
    step where the seed is a carried uint32 argument.
    """
    return jax.random.key(seed, impl=resolved_impl())
