"""Seeding (reference: `python/paddle/framework/random.py` manual_seed
sets the global program RNG seed)."""
from ..utils import flags as _flags

__all__ = ["manual_seed"]


def manual_seed(seed):
    """Set the framework-wide RNG seed (dropout/init streams derive from
    it; reference manual_seed sets Program.random_seed)."""
    _flags.set_flags({"FLAGS_seed": int(seed)})
    from ..fluid import framework as _fw

    for prog in (_fw.default_main_program(),
                 _fw.default_startup_program()):
        if prog is not None:
            prog.random_seed = int(seed)
    return seed
