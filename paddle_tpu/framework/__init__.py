"""paddle.framework 2.0 namespace (reference:
`python/paddle/framework/__init__.py`) — re-exports + seeding."""
from ..fluid.executor import Executor  # noqa: F401
from ..core.scope import global_scope  # noqa: F401
from ..fluid.backward import append_backward, gradients  # noqa: F401
from ..fluid.compiler import CompiledProgram  # noqa: F401
from ..fluid.framework import (  # noqa: F401
    default_main_program, default_startup_program, name_scope, Program,
    program_guard, Variable,
)
from ..fluid.param_attr import ParamAttr  # noqa: F401
from ..fluid.layers.tensor import (  # noqa: F401
    create_global_var, create_parameter,
)
from ..core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace,
)
from . import random  # noqa: F401
from .random import manual_seed  # noqa: F401
