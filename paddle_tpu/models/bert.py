"""BERT pretraining model (BASELINE.json config 3) in the fluid static
graph API — matmul / layer_norm / softmax / dropout stacks; masked-LM +
next-sentence heads, Adam/LAMB training.

Reference-era counterpart: the ERNIE/BERT models built on fluid layers
(multi-head attention per `layers/nn.py` primitives). TPU-native: the whole
encoder lowers to one XLA computation; attention matmuls are MXU-shaped
[B*H, S, S]; bf16-friendly (use amp.decorate for mixed precision).
"""
from __future__ import annotations

import math

from .. import fluid
from ..fluid import layers
from ..fluid.param_attr import ParamAttr


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, max_position_embeddings=512,
                 type_vocab_size=2, hidden_dropout_prob=0.1,
                 attention_probs_dropout_prob=0.1, initializer_range=0.02):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.initializer_range = initializer_range

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def tiny():
        return BertConfig(vocab_size=1024, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=128,
                          max_position_embeddings=64)


def _init(cfg):
    return fluid.initializer.TruncatedNormal(0.0, cfg.initializer_range)


def multi_head_attention(x, attn_bias, cfg, name, is_test=False):
    """x: [B, S, H]; attn_bias: [B, S] additive key bias (0 for live
    tokens, -1e4 for padding)."""
    h = cfg.hidden_size
    n_head = cfg.num_attention_heads
    d_head = h // n_head

    def proj(inp, pname):
        return layers.fc(input=inp, size=h, num_flatten_dims=2,
                         param_attr=ParamAttr(name=name + pname + ".w",
                                              initializer=_init(cfg)),
                         bias_attr=ParamAttr(name=name + pname + ".b"))

    q, k, v = proj(x, "_q"), proj(x, "_k"), proj(x, "_v")

    def to_heads(t):
        t = layers.reshape(t, [0, 0, n_head, d_head])
        return layers.transpose(t, [0, 2, 1, 3])  # [B, nH, S, dH]

    q, k, v = to_heads(q), to_heads(k), to_heads(v)
    # Fused attention: flash kernel on TPU when prob-dropout is off
    # (paddle_tpu/ops/pallas/flash_attention.py).
    ctx = layers.scaled_dot_product_attention(
        q, k, v, key_bias=attn_bias, causal=False,
        sm_scale=1.0 / math.sqrt(d_head),
        attn_dropout_prob=cfg.attention_probs_dropout_prob,
        is_test=is_test)  # [B, nH, S, dH]
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, 0, h])
    return proj(ctx, "_out")


def encoder_layer(x, attn_bias, cfg, name, is_test=False):
    attn = multi_head_attention(x, attn_bias, cfg, name + "_attn",
                                is_test=is_test)
    attn = layers.dropout(attn, cfg.hidden_dropout_prob, is_test=is_test,
                          dropout_implementation="upscale_in_train")
    x = layers.layer_norm(
        layers.elementwise_add(x, attn), begin_norm_axis=2,
        param_attr=ParamAttr(name=name + "_post_att_ln.scale"),
        bias_attr=ParamAttr(name=name + "_post_att_ln.bias"))
    ffn = layers.fc(input=x, size=cfg.intermediate_size, num_flatten_dims=2,
                    act="gelu",
                    param_attr=ParamAttr(name=name + "_ffn0.w",
                                         initializer=_init(cfg)),
                    bias_attr=ParamAttr(name=name + "_ffn0.b"))
    ffn = layers.fc(input=ffn, size=cfg.hidden_size, num_flatten_dims=2,
                    param_attr=ParamAttr(name=name + "_ffn1.w",
                                         initializer=_init(cfg)),
                    bias_attr=ParamAttr(name=name + "_ffn1.b"))
    ffn = layers.dropout(ffn, cfg.hidden_dropout_prob, is_test=is_test,
                         dropout_implementation="upscale_in_train")
    return layers.layer_norm(
        layers.elementwise_add(x, ffn), begin_norm_axis=2,
        param_attr=ParamAttr(name=name + "_post_ffn_ln.scale"),
        bias_attr=ParamAttr(name=name + "_post_ffn_ln.bias"))


def _scan_encoder_stack(x, attn_bias, cfg, is_test=False, remat=False):
    """The encoder stack as ONE `layers.Scan` over stacked [L, ...]
    parameters — the body is traced/compiled once regardless of depth
    (vs `encoder_layer` unrolling: ~12x smaller HLO, proportionally
    faster XLA compiles). Math is identical to the unrolled stack with
    q/k/v fused into one [H, 3H] projection (one MXU matmul instead of
    three). remat=True checkpoints activations per layer inside the
    scan (replaces RecomputeOptimizer segmentation for this model)."""
    from ..fluid import initializer
    from ..fluid.layers import Scan

    L, h = cfg.num_hidden_layers, cfg.hidden_size
    f = cfg.intermediate_size
    n_head = cfg.num_attention_heads
    d_head = h // n_head
    zeros = initializer.Constant(0.0)
    ones = initializer.Constant(1.0)

    def par(name, shape, init=None):
        return layers.create_parameter(
            shape=shape, dtype="float32", name=name,
            attr=ParamAttr(name=name, initializer=init or _init(cfg)))

    w_qkv = par("enc_qkv.w", [L, h, 3 * h])
    b_qkv = par("enc_qkv.b", [L, 3 * h], zeros)
    w_out = par("enc_attn_out.w", [L, h, h])
    b_out = par("enc_attn_out.b", [L, h], zeros)
    ln1_s = par("enc_post_att_ln.scale", [L, h], ones)
    ln1_b = par("enc_post_att_ln.bias", [L, h], zeros)
    w_f0 = par("enc_ffn0.w", [L, h, f])
    b_f0 = par("enc_ffn0.b", [L, f], zeros)
    w_f1 = par("enc_ffn1.w", [L, f, h])
    b_f1 = par("enc_ffn1.b", [L, h], zeros)
    ln2_s = par("enc_post_ffn_ln.scale", [L, h], ones)
    ln2_b = par("enc_post_ffn_ln.bias", [L, h], zeros)

    scan = Scan(n=L, remat=remat)
    with scan.block():
        (wqkv, bqkv, wo, bo, l1s, l1b, wf0, bf0, wf1, bf1, l2s,
         l2b) = [scan.slice_input(p) for p in (
             w_qkv, b_qkv, w_out, b_out, ln1_s, ln1_b, w_f0, b_f0,
             w_f1, b_f1, ln2_s, ln2_b)]
        qkv = layers.elementwise_add(layers.matmul(x, wqkv), bqkv)
        q = layers.slice(qkv, axes=[2], starts=[0], ends=[h])
        k = layers.slice(qkv, axes=[2], starts=[h], ends=[2 * h])
        v = layers.slice(qkv, axes=[2], starts=[2 * h], ends=[3 * h])

        def to_heads(t):
            t = layers.reshape(t, [0, 0, n_head, d_head])
            return layers.transpose(t, [0, 2, 1, 3])

        ctx = layers.scaled_dot_product_attention(
            to_heads(q), to_heads(k), to_heads(v), key_bias=attn_bias,
            causal=False, sm_scale=1.0 / math.sqrt(d_head),
            attn_dropout_prob=cfg.attention_probs_dropout_prob,
            is_test=is_test)
        ctx = layers.reshape(layers.transpose(ctx, [0, 2, 1, 3]),
                             [0, 0, h])
        attn = layers.elementwise_add(layers.matmul(ctx, wo), bo)
        attn = layers.dropout(attn, cfg.hidden_dropout_prob,
                              is_test=is_test,
                              dropout_implementation="upscale_in_train")
        y = layers.layer_norm(layers.elementwise_add(x, attn),
                              begin_norm_axis=2, scale=l1s, shift=l1b)
        ffn = layers.gelu(
            layers.elementwise_add(layers.matmul(y, wf0), bf0))
        ffn = layers.elementwise_add(layers.matmul(ffn, wf1), bf1)
        ffn = layers.dropout(ffn, cfg.hidden_dropout_prob,
                             is_test=is_test,
                             dropout_implementation="upscale_in_train")
        new_x = layers.layer_norm(layers.elementwise_add(y, ffn),
                                  begin_norm_axis=2, scale=l2s,
                                  shift=l2b)
        layers.assign(new_x, output=x)
    return x


def bert_encoder(src_ids, pos_ids, sent_ids, input_mask, cfg,
                 is_test=False, checkpoints_out=None, scan_layers=False,
                 scan_remat=False):
    """Returns [B, S, H] sequence output. When `checkpoints_out` is a
    list, each encoder layer's output var is appended — the natural
    remat segmentation for RecomputeOptimizer (PERF_ANALYSIS_r4:
    batch 512 needs activation checkpointing to fit 16G HBM).
    scan_layers=True builds the stack as one layers.Scan
    (`_scan_encoder_stack`) — per-layer checkpointing then comes from
    scan_remat, not RecomputeOptimizer."""
    emb = layers.embedding(src_ids, size=[cfg.vocab_size, cfg.hidden_size],
                           param_attr=ParamAttr(name="word_embedding",
                                                initializer=_init(cfg)))
    pos = layers.embedding(pos_ids,
                           size=[cfg.max_position_embeddings,
                                 cfg.hidden_size],
                           param_attr=ParamAttr(name="pos_embedding",
                                                initializer=_init(cfg)))
    sent = layers.embedding(sent_ids,
                            size=[cfg.type_vocab_size, cfg.hidden_size],
                            param_attr=ParamAttr(name="sent_embedding",
                                                 initializer=_init(cfg)))
    x = layers.elementwise_add(layers.elementwise_add(emb, pos), sent)
    x = layers.layer_norm(x, begin_norm_axis=2,
                          param_attr=ParamAttr(name="pre_encoder_ln.scale"),
                          bias_attr=ParamAttr(name="pre_encoder_ln.bias"))
    x = layers.dropout(x, cfg.hidden_dropout_prob, is_test=is_test,
                       dropout_implementation="upscale_in_train")

    # additive [B, S] key bias from the [B, S] mask: (1-m) * -1e4
    attn_bias = layers.scale(input_mask, scale=-10000.0, bias=10000.0)

    if scan_layers:
        return _scan_encoder_stack(x, attn_bias, cfg, is_test=is_test,
                                   remat=scan_remat)
    for i in range(cfg.num_hidden_layers):
        x = encoder_layer(x, attn_bias, cfg, "layer_%d" % i,
                          is_test=is_test)
        if checkpoints_out is not None:
            checkpoints_out.append(x)
    return x


def bert_pretrain_loss(cfg, seq_len, is_test=False,
                       checkpoints_out=None, scan_layers=False,
                       scan_remat=False):
    """Masked-LM + next-sentence pretraining loss over feed vars.

    Masked positions are a dense [B, max_pred] per-sequence index tensor
    with a [B, max_pred] weight mask (padded slots get weight 0) —
    XLA-friendly static shapes, SURVEY.md §7 hard part (a). The gather is
    a batched take_along_axis on [B, S, H] (small per-row index space;
    its vjp is a batched segment scatter), NOT a flat gather over
    [B*S, H] whose backward scatter serializes on TPU. The vocab head is
    the fused_linear_softmax_xent op, so [tokens, vocab] logits are never
    materialized (round-2 profile: that buffer + its softmax were the
    largest HBM cost in the step and the batch-512 OOM)."""
    src = layers.data(name="src_ids", shape=[seq_len], dtype="int64")
    pos = layers.data(name="pos_ids", shape=[seq_len], dtype="int64")
    sent = layers.data(name="sent_ids", shape=[seq_len], dtype="int64")
    mask = layers.data(name="input_mask", shape=[seq_len], dtype="float32")
    mask_pos = layers.data(name="mask_pos", shape=[None], dtype="int64")
    mask_label = layers.data(name="mask_label", shape=[None], dtype="int64")
    mask_weight = layers.data(name="mask_weight", shape=[None],
                              dtype="float32")
    nsp_label = layers.data(name="nsp_label", shape=[1], dtype="int64")

    seq_out = bert_encoder(src, pos, sent, mask, cfg, is_test=is_test,
                           checkpoints_out=checkpoints_out,
                           scan_layers=scan_layers,
                           scan_remat=scan_remat)

    # -- masked LM head (batched take_along_axis of masked positions) --
    idx = layers.reshape(mask_pos, [0, -1, 1])  # [B, P, 1]
    picked = layers.take_along_axis(seq_out, idx, axis=1)  # [B, P, H]
    picked = layers.reshape(picked, [-1, cfg.hidden_size])
    trans = layers.fc(input=picked, size=cfg.hidden_size, act="gelu",
                      param_attr=ParamAttr(name="mlm_trans.w",
                                           initializer=_init(cfg)),
                      bias_attr=ParamAttr(name="mlm_trans.b"))
    trans = layers.layer_norm(trans, begin_norm_axis=1,
                              param_attr=ParamAttr(name="mlm_ln.scale"),
                              bias_attr=ParamAttr(name="mlm_ln.bias"))
    per_tok = layers.loss.fused_linear_softmax_xent(
        trans, layers.reshape(mask_label, [-1, 1]), cfg.vocab_size,
        param_attr=ParamAttr(name="mlm_out.w", initializer=_init(cfg)),
        bias_attr=ParamAttr(name="mlm_out.b"))  # [B*P, 1]
    w_flat = layers.reshape(mask_weight, [-1, 1])
    denom = layers.scale(layers.reduce_sum(w_flat), bias=1e-6)
    mlm_loss = layers.elementwise_div(
        layers.reduce_sum(layers.elementwise_mul(per_tok, w_flat)), denom)

    # -- next sentence head over [CLS] --
    cls = layers.slice(seq_out, axes=[1], starts=[0], ends=[1])
    cls = layers.reshape(cls, [-1, cfg.hidden_size])
    pooled = layers.fc(input=cls, size=cfg.hidden_size, act="tanh",
                       param_attr=ParamAttr(name="pooler.w",
                                            initializer=_init(cfg)),
                       bias_attr=ParamAttr(name="pooler.b"))
    nsp_logits = layers.fc(input=pooled, size=2,
                           param_attr=ParamAttr(name="nsp.w",
                                                initializer=_init(cfg)),
                           bias_attr=ParamAttr(name="nsp.b"))
    nsp_loss = layers.mean(
        layers.softmax_with_cross_entropy(nsp_logits, nsp_label))

    total = layers.elementwise_add(mlm_loss, nsp_loss)
    feeds = ["src_ids", "pos_ids", "sent_ids", "input_mask", "mask_pos",
             "mask_label", "mask_weight", "nsp_label"]
    return total, mlm_loss, nsp_loss, feeds


def build_bert_pretrain(cfg=None, seq_len=128, lr=1e-4, use_lamb=False,
                        weight_decay=0.01, is_test=False):
    cfg = cfg or BertConfig.base()
    total, mlm_loss, nsp_loss, feeds = bert_pretrain_loss(
        cfg, seq_len, is_test=is_test)
    if not is_test:
        def exclude(p):
            return "ln" in p.name or ".b" in p.name

        if use_lamb:
            opt = fluid.optimizer.LambOptimizer(
                learning_rate=lr, lamb_weight_decay=weight_decay,
                exclude_from_weight_decay_fn=exclude)
        else:
            opt = fluid.optimizer.AdamOptimizer(learning_rate=lr)
        opt.minimize(total)
    return total, mlm_loss, nsp_loss, feeds
