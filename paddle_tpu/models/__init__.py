"""Model zoo: reference workloads from BASELINE.json configs."""
