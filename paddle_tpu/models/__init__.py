"""Model zoo: the reference workloads from BASELINE.json configs.

1. MNIST MLP/conv (mnist.py)        — static graph smoke model
2. ResNet-{18,34,50,101,152} (resnet.py) — ImageNet classification
3. BERT-base pretraining (bert.py)  — MLM + NSP
4. Transformer WMT en-de (transformer.py) — + jittable beam search
5. CTR wide&deep / DLRM-tiny (ctr.py) — the sparse-embedding
   recommender family (vocab-sharded tables, paddle_tpu/embedding)
"""
from . import mnist  # noqa: F401
from . import resnet  # noqa: F401
from . import bert  # noqa: F401
from . import transformer  # noqa: F401
from . import ctr  # noqa: F401
