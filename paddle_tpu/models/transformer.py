"""Transformer seq2seq for WMT en-de (BASELINE.json config 4): fluid
static-graph training + jittable beam-search inference.

Reference counterparts: the fluid Transformer model
(`dist_transformer.py` test model, `layers/nn.py` primitives) and the
beam-search ops (`operators/beam_search_op.cc`,
`layers/rnn.py` dynamic_decode). TPU-native inference: beam search is a
`lax.while_loop` with a static-shape KV cache (SURVEY.md §3F TPU mapping —
"beam-search decode needs a jit-able while-loop implementation"), reading
trained parameters straight from the Scope (device-resident arrays), so
train->decode needs no format conversion.
"""
from __future__ import annotations

import math

import numpy as np

from .. import fluid
from ..fluid import layers
from ..fluid.param_attr import ParamAttr


class TransformerConfig:
    def __init__(self, src_vocab=32000, tgt_vocab=32000, max_len=256,
                 d_model=512, n_head=8, d_ff=2048, n_layer=6, dropout=0.1):
        self.src_vocab = src_vocab
        self.tgt_vocab = tgt_vocab
        self.max_len = max_len
        self.d_model = d_model
        self.n_head = n_head
        self.d_ff = d_ff
        self.n_layer = n_layer
        self.dropout = dropout

    @staticmethod
    def big():
        return TransformerConfig(d_model=1024, n_head=16, d_ff=4096)

    @staticmethod
    def tiny():
        return TransformerConfig(src_vocab=128, tgt_vocab=128, max_len=16,
                                 d_model=32, n_head=4, d_ff=64, n_layer=2,
                                 dropout=0.0)


def _init():
    return fluid.initializer.Xavier(uniform=True)


def _proj(x, size, name, act=None):
    return layers.fc(input=x, size=size, num_flatten_dims=2, act=act,
                     param_attr=ParamAttr(name=name + ".w",
                                          initializer=_init()),
                     bias_attr=ParamAttr(name=name + ".b"))


def _attention_core(q, k, v, bias, cfg, is_test, out_proj):
    """softmax(QK^T/sqrt(d_head)+bias)V over heads; q/k/v are already
    [B, S, d_model] projections, out_proj maps the context back. ONE
    copy of the weight-parity-critical math shared by the unrolled path
    and the scan body."""
    d_head = cfg.d_model // cfg.n_head

    def heads(t):
        t = layers.reshape(t, [0, 0, cfg.n_head, d_head])
        return layers.transpose(t, [0, 2, 1, 3])

    q, k, v = heads(q), heads(k), heads(v)
    scores = layers.matmul(q, k, transpose_y=True,
                           alpha=1.0 / math.sqrt(d_head))
    if bias is not None:
        scores = layers.elementwise_add(scores, bias)
    probs = layers.softmax(scores)
    if cfg.dropout and not is_test:
        probs = layers.dropout(probs, cfg.dropout, is_test=is_test,
                               dropout_implementation="upscale_in_train")
    ctx = layers.transpose(layers.matmul(probs, v), [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, 0, cfg.d_model])
    return out_proj(ctx)


def _attention(q_in, kv_in, bias, cfg, name, is_test):
    return _attention_core(
        _proj(q_in, cfg.d_model, name + "_q"),
        _proj(kv_in, cfg.d_model, name + "_k"),
        _proj(kv_in, cfg.d_model, name + "_v"),
        bias, cfg, is_test,
        lambda ctx: _proj(ctx, cfg.d_model, name + "_o"))


def _ln(x, name):
    return layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name=name + ".scale"),
        bias_attr=ParamAttr(name=name + ".bias"))


def _ffn(x, cfg, name):
    h = _proj(x, cfg.d_ff, name + "_fc0", act="relu")
    return _proj(h, cfg.d_model, name + "_fc1")


def _embed(ids, vocab, cfg, name, pos_name="pos_enc"):
    emb = layers.embedding(ids, size=[vocab, cfg.d_model],
                           param_attr=ParamAttr(name=name,
                                                initializer=_init()))
    emb = layers.scale(emb, scale=math.sqrt(cfg.d_model))
    seq_len = emb.shape[1]
    pe = _positional_encoding(cfg.max_len, cfg.d_model)
    pe_var = layers.assign(pe[:seq_len])
    return layers.elementwise_add(emb, layers.unsqueeze(pe_var, [0]))


def _positional_encoding(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype("float32")
    i = np.arange(d_model // 2)[None, :].astype("float32")
    angle = pos / np.power(10000.0, 2 * i / d_model)
    pe = np.zeros((max_len, d_model), "float32")
    pe[:, 0::2] = np.sin(angle)
    pe[:, 1::2] = np.cos(angle)
    return pe


def encoder(src_ids, src_bias, cfg, is_test=False, scan_layers=False,
            scan_remat=False):
    x = _embed(src_ids, cfg.src_vocab, cfg, "src_word_emb")
    if scan_layers:
        return _scan_stack(x, cfg, "enc", is_test, self_bias=src_bias,
                           remat=scan_remat)
    for i in range(cfg.n_layer):
        nm = "enc_%d" % i
        attn = _attention(x, x, src_bias, cfg, nm + "_selfattn", is_test)
        x = _ln(layers.elementwise_add(x, attn), nm + "_ln0")
        ffn = _ffn(x, cfg, nm + "_ffn")
        x = _ln(layers.elementwise_add(x, ffn), nm + "_ln1")
    return x


def decoder(tgt_ids, enc_out, self_bias, cross_bias, cfg, is_test=False,
            scan_layers=False, scan_remat=False):
    x = _embed(tgt_ids, cfg.tgt_vocab, cfg, "tgt_word_emb")
    if scan_layers:
        x = _scan_stack(x, cfg, "dec", is_test, self_bias=self_bias,
                        cross_kv=enc_out, cross_bias=cross_bias,
                        remat=scan_remat)
        return _proj(x, cfg.tgt_vocab, "dec_out_proj")
    for i in range(cfg.n_layer):
        nm = "dec_%d" % i
        attn = _attention(x, x, self_bias, cfg, nm + "_selfattn", is_test)
        x = _ln(layers.elementwise_add(x, attn), nm + "_ln0")
        cross = _attention(x, enc_out, cross_bias, cfg, nm + "_crossattn",
                           is_test)
        x = _ln(layers.elementwise_add(x, cross), nm + "_ln1")
        ffn = _ffn(x, cfg, nm + "_ffn")
        x = _ln(layers.elementwise_add(x, ffn), nm + "_ln2")
    return _proj(x, cfg.tgt_vocab, "dec_out_proj")


def _scan_stack(x, cfg, prefix, is_test, self_bias=None, cross_kv=None,
                cross_bias=None, remat=False):
    """Encoder/decoder layer stack as ONE layers.Scan over stacked
    [L, ...] params (see models/bert._scan_encoder_stack). Stacked
    names mirror the unrolled ones with the layer index replaced by
    'stack' (enc_0_selfattn_q.w -> enc_stack_selfattn_q.w [L, d, d]),
    so beam_search_decode can expand them back per layer."""
    from ..fluid.layers import Scan

    L, d, f = cfg.n_layer, cfg.d_model, cfg.d_ff
    zeros = fluid.initializer.Constant(0.0)
    ones = fluid.initializer.Constant(1.0)

    def par(suffix, shape, init=None):
        name = "%s_stack%s" % (prefix, suffix)
        if init is None and len(shape) == 3:
            # Xavier fan must come from the per-LAYER 2D slice, not the
            # stacked 3D shape (which would under-scale the init ~16x
            # vs the unrolled path this stack is weight-parity with)
            init = fluid.initializer.Xavier(
                uniform=True, fan_in=shape[1], fan_out=shape[2])
        return layers.create_parameter(
            shape=shape, dtype="float32", name=name,
            attr=ParamAttr(name=name, initializer=init or _init()))

    def attn_pack(kind):
        return {p: (par("%s_%s.w" % (kind, p), [L, d, d]),
                    par("%s_%s.b" % (kind, p), [L, d], zeros))
                for p in ("q", "k", "v", "o")}

    packs = {"_selfattn": attn_pack("_selfattn")}
    lns = [("_ln0", par("_ln0.scale", [L, d], ones),
            par("_ln0.bias", [L, d], zeros)),
           ("_ln1", par("_ln1.scale", [L, d], ones),
            par("_ln1.bias", [L, d], zeros))]
    if cross_kv is not None:
        packs["_crossattn"] = attn_pack("_crossattn")
        lns.append(("_ln2", par("_ln2.scale", [L, d], ones),
                    par("_ln2.bias", [L, d], zeros)))
    w_f0 = par("_ffn_fc0.w", [L, d, f])
    b_f0 = par("_ffn_fc0.b", [L, f], zeros)
    w_f1 = par("_ffn_fc1.w", [L, f, d])
    b_f1 = par("_ffn_fc1.b", [L, d], zeros)

    scan = Scan(n=L, remat=remat)
    with scan.block():
        sl = {}
        for kind, pk in packs.items():
            sl[kind] = {p: (scan.slice_input(w), scan.slice_input(b))
                        for p, (w, b) in pk.items()}
        ln_sl = [(nm, scan.slice_input(s), scan.slice_input(b))
                 for nm, s, b in lns]
        f0w, f0b = scan.slice_input(w_f0), scan.slice_input(b_f0)
        f1w, f1b = scan.slice_input(w_f1), scan.slice_input(b_f1)

        def proj(inp, w, b):
            return layers.elementwise_add(layers.matmul(inp, w), b)

        # _attention_core: ONE copy of the math (weight-parity with the
        # unrolled path); the fused scaled_dot_product_attention path
        # only changes the lowering at seq >=
        # FLAGS_flash_attention_min_seq (4096), far above WMT's max_len
        def attn(q_in, kv_in, bias, kind):
            s = sl[kind]
            return _attention_core(
                proj(q_in, *s["q"]), proj(kv_in, *s["k"]),
                proj(kv_in, *s["v"]), bias, cfg, is_test,
                lambda ctx: proj(ctx, *s["o"]))

        def ln_i(inp, i):
            _, s, b = ln_sl[i]
            return layers.layer_norm(inp, begin_norm_axis=2, scale=s,
                                     shift=b)

        y = ln_i(layers.elementwise_add(
            x, attn(x, x, self_bias, "_selfattn")), 0)
        nxt = 1
        if cross_kv is not None:
            y = ln_i(layers.elementwise_add(
                y, attn(y, cross_kv, cross_bias, "_crossattn")), 1)
            nxt = 2
        ffn = layers.elementwise_add(
            layers.matmul(layers.relu(proj(y, f0w, f0b)), f1w), f1b)
        new_x = ln_i(layers.elementwise_add(y, ffn), nxt)
        layers.assign(new_x, output=x)
    return x


def build_transformer_train(cfg=None, src_len=32, tgt_len=32, lr=1e-3,
                            warmup=4000, label_smooth_eps=0.1,
                            is_test=False, scan_layers=False,
                            scan_remat=False):
    """Teacher-forced training graph. Returns (avg_loss, feeds)."""
    cfg = cfg or TransformerConfig()
    src = layers.data(name="src_ids", shape=[src_len], dtype="int64")
    tgt = layers.data(name="tgt_ids", shape=[tgt_len], dtype="int64")
    lbl = layers.data(name="lbl_ids", shape=[tgt_len], dtype="int64")
    src_mask = layers.data(name="src_mask", shape=[src_len],
                           dtype="float32")
    tgt_mask = layers.data(name="tgt_mask", shape=[tgt_len],
                           dtype="float32")

    src_bias = layers.unsqueeze(layers.unsqueeze(
        layers.scale(src_mask, scale=-1e4, bias=1e4), [1]), [1])
    # causal + padding bias for decoder self-attention
    causal = np.triu(np.full((tgt_len, tgt_len), -1e4, "float32"), k=1)
    causal_var = layers.assign(causal)
    pad_bias = layers.unsqueeze(layers.unsqueeze(
        layers.scale(tgt_mask, scale=-1e4, bias=1e4), [1]), [1])
    self_bias = layers.elementwise_add(pad_bias, causal_var)
    cross_bias = src_bias

    enc_out = encoder(src, src_bias, cfg, is_test,
                      scan_layers=scan_layers, scan_remat=scan_remat)
    logits = decoder(tgt, enc_out, self_bias, cross_bias, cfg, is_test,
                     scan_layers=scan_layers, scan_remat=scan_remat)

    if label_smooth_eps:
        oh = layers.one_hot(layers.unsqueeze(lbl, [2]), cfg.tgt_vocab)
        smoothed = layers.label_smooth(oh, epsilon=label_smooth_eps)
        loss = layers.softmax_with_cross_entropy(logits, smoothed,
                                                 soft_label=True)
    else:
        loss = layers.softmax_with_cross_entropy(
            logits, layers.unsqueeze(lbl, [2]))
    w = layers.unsqueeze(tgt_mask, [2])
    loss = layers.elementwise_mul(loss, w)
    avg_loss = layers.elementwise_div(
        layers.reduce_sum(loss), layers.reduce_sum(w) + 1e-9)
    if not is_test:
        lr_var = layers.noam_decay(cfg.d_model, warmup, lr)
        opt = fluid.optimizer.AdamOptimizer(
            learning_rate=lr_var, beta1=0.9, beta2=0.997, epsilon=1e-9)
        opt.minimize(avg_loss)
    return avg_loss, ["src_ids", "tgt_ids", "lbl_ids", "src_mask",
                      "tgt_mask"]


# ---------------------------------------------------------------------------
# jittable beam-search inference (lax.while_loop, static shapes)
# ---------------------------------------------------------------------------

def _np_params(scope, names):
    """Collect params by their unrolled names; when a model was trained
    with scan_layers=True the scope holds the stacked '<pre>_stack*'
    arrays instead — expand slice [i] of the stacked array for the
    per-layer name 'pre_i_rest'."""
    import re

    out = {}
    for n in names:
        v = scope.find_var(n)
        if v is None:
            m = re.match(r"^(enc|dec)_(\d+)(_.*)$", n)
            if m:
                stacked = scope.find_var(
                    "%s_stack%s" % (m.group(1), m.group(3)))
                if stacked is not None:
                    out[n] = stacked[int(m.group(2))]
                    continue
            raise RuntimeError("param %r missing from scope" % n)
        out[n] = v
    return out


def layer_param_suffixes(pre):
    """THE per-layer parameter suffix list for an encoder ('enc') or
    decoder ('dec') layer — single source for the unrolled names
    ('enc_3' + suffix), the scan-stacked names ('enc_stack' + suffix),
    _np_params' expansion, and the tests' stacking helpers."""
    kinds = ["_selfattn"] + (["_crossattn"] if pre == "dec" else [])
    sufs = []
    for a in kinds:
        for p in ("_q", "_k", "_v", "_o"):
            sufs += [a + p + ".w", a + p + ".b"]
    for f in ("_ffn_fc0", "_ffn_fc1"):
        sufs += [f + ".w", f + ".b"]
    lns = ("_ln0", "_ln1") if pre == "enc" else ("_ln0", "_ln1", "_ln2")
    for ln in lns:
        sufs += [ln + ".scale", ln + ".bias"]
    return sufs


def _collect_param_names(cfg):
    names = ["src_word_emb", "tgt_word_emb"]
    for pre, n in (("enc", cfg.n_layer), ("dec", cfg.n_layer)):
        for i in range(n):
            names += ["%s_%d%s" % (pre, i, suf)
                      for suf in layer_param_suffixes(pre)]
    names += ["dec_out_proj.w", "dec_out_proj.b"]
    return names


def beam_search_decode(scope, src_ids, src_mask, cfg, beam_size=4,
                       max_out_len=32, bos_id=0, eos_id=1, alpha=0.6):
    """Jittable beam search over the trained scope params.

    src_ids: [B, S] int; src_mask: [B, S] float. Returns
    (seqs [B, beam, T], scores [B, beam]).
    """
    import jax
    import jax.numpy as jnp

    p = _np_params(scope, _collect_param_names(cfg))
    d_head = cfg.d_model // cfg.n_head

    def ln(x, nm):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), -1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * p[nm + ".scale"] \
            + p[nm + ".bias"]

    def proj(x, nm):
        return x @ p[nm + ".w"] + p[nm + ".b"]

    def heads(t):
        return t.reshape(t.shape[:-1] + (cfg.n_head, d_head)) \
            .swapaxes(-3, -2)

    def attn(q_in, k, v, bias, nm):
        q = heads(proj(q_in, nm + "_q"))
        s = q @ k.swapaxes(-1, -2) / math.sqrt(d_head)
        if bias is not None:
            s = s + bias
        probs = jax.nn.softmax(s, -1)
        ctx = (probs @ v).swapaxes(-3, -2)
        ctx = ctx.reshape(ctx.shape[:-2] + (cfg.d_model,))
        return proj(ctx, nm + "_o")

    pe = jnp.asarray(_positional_encoding(cfg.max_len, cfg.d_model))

    def embed(ids, table, offset):
        e = jnp.take(p[table], ids, axis=0) * math.sqrt(cfg.d_model)
        return e + jax.lax.dynamic_slice_in_dim(pe, offset,
                                                ids.shape[-1], 0)

    def run_encoder(src, src_bias):
        x = embed(src, "src_word_emb", 0)
        for i in range(cfg.n_layer):
            nm = "enc_%d" % i
            k = heads(proj(x, nm + "_selfattn_k"))
            v = heads(proj(x, nm + "_selfattn_v"))
            x = ln(x + attn(x, k, v, src_bias, nm + "_selfattn"),
                   nm + "_ln0")
            h = jax.nn.relu(proj(x, nm + "_ffn_fc0"))
            x = ln(x + proj(h, nm + "_ffn_fc1"), nm + "_ln1")
        return x

    @jax.jit
    def decode(src, mask):
        B, S = src.shape
        K = beam_size
        T = max_out_len
        src_bias = ((1.0 - mask) * -1e4)[:, None, None, :]
        enc = run_encoder(src, src_bias)

        # expand to beams: [B*K, ...]
        enc_b = jnp.repeat(enc, K, axis=0)
        bias_b = jnp.repeat(src_bias, K, axis=0)
        # precompute cross K/V per layer
        cross_kv = []
        for i in range(cfg.n_layer):
            nm = "dec_%d_crossattn" % i
            cross_kv.append((heads(proj(enc_b, nm + "_k")),
                             heads(proj(enc_b, nm + "_v"))))

        seqs = jnp.full((B * K, T + 1), eos_id, jnp.int32)
        seqs = seqs.at[:, 0].set(bos_id)
        # beam scores: first beam 0, rest -inf so step 1 picks distinct
        scores = jnp.tile(jnp.asarray([0.0] + [-1e9] * (K - 1),
                                      jnp.float32), (B,))
        finished = jnp.zeros((B * K,), bool)
        # static KV cache [B*K, nH, T, dH] per layer
        cache = [(jnp.zeros((B * K, cfg.n_head, T, d_head)),
                  jnp.zeros((B * K, cfg.n_head, T, d_head)))
                 for _ in range(cfg.n_layer)]

        def step(t, carry):
            seqs, scores, finished, cache = carry
            tok = jax.lax.dynamic_slice_in_dim(seqs, t, 1, 1)  # [B*K,1]
            x = embed(tok, "tgt_word_emb", t)
            new_cache = []
            for i in range(cfg.n_layer):
                nm = "dec_%d" % i
                k_new = heads(proj(x, nm + "_selfattn_k"))  # [B*K,nH,1,dH]
                v_new = heads(proj(x, nm + "_selfattn_v"))
                ck, cv = cache[i]
                ck = jax.lax.dynamic_update_slice_in_dim(ck, k_new, t, 2)
                cv = jax.lax.dynamic_update_slice_in_dim(cv, v_new, t, 2)
                new_cache.append((ck, cv))
                # causal: positions > t are masked
                tmask = (jnp.arange(T) > t) * -1e9
                a = attn(x, ck, cv, tmask[None, None, None, :],
                         nm + "_selfattn")
                x = ln(x + a, nm + "_ln0")
                ki, vi = cross_kv[i]
                x = ln(x + attn(x, ki, vi, bias_b, nm + "_crossattn"),
                       nm + "_ln1")
                h = jax.nn.relu(proj(x, nm + "_ffn_fc0"))
                x = ln(x + proj(h, nm + "_ffn_fc1"), nm + "_ln2")
            logits = proj(x[:, 0], "dec_out_proj")  # [B*K, V]
            logp = jax.nn.log_softmax(logits, -1)
            # finished beams only extend with eos at zero cost
            V = cfg.tgt_vocab
            eos_only = jnp.full((V,), -1e9).at[eos_id].set(0.0)
            logp = jnp.where(finished[:, None], eos_only[None, :], logp)

            cand = scores[:, None] + logp  # [B*K, V]
            cand = cand.reshape(B, K * V)
            top_scores, top_idx = jax.lax.top_k(cand, K)  # [B, K]
            beam_idx = top_idx // V + jnp.arange(B)[:, None] * K
            tok_idx = (top_idx % V).astype(jnp.int32)
            flat_beam = beam_idx.reshape(-1)
            seqs = seqs[flat_beam]
            seqs = jax.lax.dynamic_update_slice_in_dim(
                seqs, tok_idx.reshape(-1, 1), t + 1, 1)
            scores = top_scores.reshape(-1)
            finished = finished[flat_beam] | (tok_idx.reshape(-1) == eos_id)
            cache = [(ck[flat_beam], cv[flat_beam])
                     for ck, cv in new_cache]
            return seqs, scores, finished, cache

        def cond(state):
            t, carry = state
            return (t < T) & ~jnp.all(carry[2])

        def body(state):
            t, carry = state
            return t + 1, step(t, carry)

        _, (seqs, scores, finished, _) = jax.lax.while_loop(
            cond, body, (0, (seqs, scores, finished, cache)))
        # length penalty (GNMT alpha)
        lengths = jnp.sum((seqs[:, 1:] != eos_id).astype(jnp.float32), -1) \
            + 1.0
        lp = jnp.power((5.0 + lengths) / 6.0, alpha)
        final = (seqs.reshape(B, K, T + 1),
                 (scores / lp).reshape(B, K))
        return final

    import jax.numpy as jnp2

    return decode(jnp2.asarray(np.asarray(src_ids, "int32")),
                  jnp2.asarray(np.asarray(src_mask, "float32")))
