"""MNIST models (BASELINE.json config 1; reference:
`python/paddle/fluid/tests/book/test_recognize_digits.py`)."""
from __future__ import annotations

from .. import fluid
from ..fluid import layers


def mlp(img, hidden_sizes=(200, 200), class_dim=10):
    h = img
    for size in hidden_sizes:
        h = layers.fc(input=h, size=size, act="relu")
    return layers.fc(input=h, size=class_dim)


def conv_net(img, class_dim=10):
    """LeNet-ish conv net (reference: test_recognize_digits.py:65)."""
    conv1 = layers.conv2d(input=img, num_filters=20, filter_size=5,
                          act="relu")
    pool1 = layers.pool2d(conv1, pool_size=2, pool_stride=2)
    conv2 = layers.conv2d(input=pool1, num_filters=50, filter_size=5,
                          act="relu")
    pool2 = layers.pool2d(conv2, pool_size=2, pool_stride=2)
    return layers.fc(input=pool2, size=class_dim)


def build_mnist_train(arch="mlp", lr=0.01):
    if arch == "conv":
        img = layers.data(name="img", shape=[1, 28, 28], dtype="float32")
        logits = conv_net(img)
    else:
        img = layers.data(name="img", shape=[784], dtype="float32")
        logits = mlp(img)
    label = layers.data(name="label", shape=[1], dtype="int64")
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(input=layers.softmax(logits), label=label)
    fluid.optimizer.AdamOptimizer(learning_rate=lr).minimize(loss)
    return loss, acc, ["img", "label"]
