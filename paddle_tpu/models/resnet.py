"""ResNet for ImageNet classification (BASELINE.json config 2).

Built with the fluid static-graph layers API the way reference users do
(cf. the model zoo style used by `tests/book` and `dist_se_resnext.py`
in `python/paddle/fluid/tests/unittests/`): conv2d + batch_norm + pool2d
bottleneck stacks. On TPU the whole train step lowers to one XLA
computation; convs hit the MXU via lax.conv_general_dilated.
"""
from __future__ import annotations

from .. import fluid
from ..fluid import layers
from ..fluid.param_attr import ParamAttr

DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, name=None, is_test=False):
    conv = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        act=None, bias_attr=False,
        param_attr=ParamAttr(name=name + "_weights" if name else None))
    return layers.batch_norm(
        input=conv, act=act, is_test=is_test,
        param_attr=ParamAttr(name=name + "_bn_scale" if name else None),
        bias_attr=ParamAttr(name=name + "_bn_offset" if name else None),
        moving_mean_name=name + "_bn_mean" if name else None,
        moving_variance_name=name + "_bn_var" if name else None)


def shortcut(input, ch_out, stride, name, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, name=name,
                             is_test=is_test)
    return input


def bottleneck_block(input, num_filters, stride, name, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu",
                          name=name + "_branch2a", is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride, act="relu",
                          name=name + "_branch2b", is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, act=None,
                          name=name + "_branch2c", is_test=is_test)
    short = shortcut(input, num_filters * 4, stride,
                     name=name + "_branch1", is_test=is_test)
    return layers.relu(layers.elementwise_add(short, conv2))


def basic_block(input, num_filters, stride, name, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 3, stride=stride, act="relu",
                          name=name + "_branch2a", is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, act=None,
                          name=name + "_branch2b", is_test=is_test)
    short = shortcut(input, num_filters, stride, name=name + "_branch1",
                     is_test=is_test)
    return layers.relu(layers.elementwise_add(short, conv1))


def _bn_with_vars(x, scale, bias, mean, var, is_test, act=None,
                  momentum=0.9):
    """batch_norm over EXISTING scale/bias/mean/var vars, returning
    (y, mean_out, var_out) as fresh vars — the scan body feeds
    per-iteration slices in and scatters the new stats back, instead of
    the layer's in-place moving-stat update."""
    from ..fluid.layer_helper import LayerHelper, apply_op

    helper = LayerHelper("batch_norm", act=act)
    outs = apply_op(
        helper, "batch_norm",
        {"X": [x], "Scale": [scale], "Bias": [bias], "Mean": [mean],
         "Variance": [var]},
        {"momentum": momentum, "epsilon": 1e-5, "is_test": is_test,
         "data_layout": "NCHW"},
        ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
        out_dtype=x.dtype)
    return helper.append_activation(outs[0]), outs[1], outs[2]


def _scan_stage_tail(x, n_rep, num_filters, name, is_test):
    """Blocks 1..count-1 of a bottleneck stage as ONE layers.Scan:
    identical (stride-1, identity-shortcut) bottlenecks over stacked
    [L, ...] conv filters and BN affine params; BN running stats live
    as stacked [L, C] vars updated per iteration via
    scan.iteration() + gather/scatter. Math is identical to the
    unrolled blocks (parity-tested under shared weights)."""
    import math as _math

    from ..fluid.layers import Scan

    L, f = n_rep, num_filters
    C = f * 4
    zeros = fluid.initializer.Constant(0.0)
    ones = fluid.initializer.Constant(1.0)
    convs = [("2a", f, C, 1), ("2b", f, f, 3), ("2c", C, f, 1)]
    w_stk, aff_stk, stats = {}, {}, {}
    for suf, oc, ic, k in convs:
        fan_in = ic * k * k
        w_stk[suf] = layers.create_parameter(
            shape=[L, oc, ic, k, k], dtype="float32",
            name=name + suf + "_weights",
            attr=ParamAttr(
                name=name + suf + "_weights",
                initializer=fluid.initializer.Normal(
                    0.0, _math.sqrt(2.0 / fan_in))))
        aff_stk[suf] = (
            layers.create_parameter(
                shape=[L, oc], dtype="float32",
                name=name + suf + "_bn_scale",
                attr=ParamAttr(name=name + suf + "_bn_scale",
                               initializer=ones)),
            layers.create_parameter(
                shape=[L, oc], dtype="float32",
                name=name + suf + "_bn_offset",
                attr=ParamAttr(name=name + suf + "_bn_offset",
                               initializer=zeros)))
        mean_v = layers.create_global_var(
            [L, oc], 0.0, "float32", persistable=True,
            name=name + suf + "_bn_mean")
        var_v = layers.create_global_var(
            [L, oc], 1.0, "float32", persistable=True,
            name=name + suf + "_bn_var")
        mean_v.stop_gradient = var_v.stop_gradient = True
        stats[suf] = (mean_v, var_v)

    scan = Scan(n=L)
    with scan.block():
        idx = scan.iteration()
        w_sl = {suf: scan.slice_input(w_stk[suf]) for suf, *_ in convs}
        aff_sl = {suf: (scan.slice_input(aff_stk[suf][0]),
                        scan.slice_input(aff_stk[suf][1]))
                  for suf, *_ in convs}

        def conv_bn(xin, suf, oc, k, act):
            conv = layers.conv2d(xin, oc, k, stride=1,
                                 padding=(k - 1) // 2,
                                 param_attr=w_sl[suf], bias_attr=False)
            mean_stk, var_stk = stats[suf]
            mean_row = layers.reshape(layers.gather(mean_stk, idx), [-1])
            var_row = layers.reshape(layers.gather(var_stk, idx), [-1])
            y, mean_out, var_out = _bn_with_vars(
                conv, aff_sl[suf][0], aff_sl[suf][1], mean_row, var_row,
                is_test, act=act)
            if not is_test:
                layers.assign(layers.scatter(
                    mean_stk, idx, layers.reshape(mean_out, [1, -1])),
                    output=mean_stk)
                layers.assign(layers.scatter(
                    var_stk, idx, layers.reshape(var_out, [1, -1])),
                    output=var_stk)
            return y

        h = conv_bn(x, "2a", f, 1, "relu")
        h = conv_bn(h, "2b", f, 3, "relu")
        h = conv_bn(h, "2c", C, 1, None)
        new_x = layers.relu(layers.elementwise_add(x, h))
        layers.assign(new_x, output=x)
    return x


def resnet(input, class_dim=1000, depth=50, is_test=False,
           scan_stages=False):
    """Build the logits head over `input` (NCHW float). scan_stages:
    run each stage's identical tail blocks as one layers.Scan
    (bottleneck depths only) — ~2x smaller HLO / faster compiles with
    identical math."""
    block_type, counts = DEPTH_CFG[depth]
    block_fn = bottleneck_block if block_type == "bottleneck" \
        else basic_block
    if scan_stages and block_type != "bottleneck":
        raise ValueError("scan_stages supports bottleneck depths only")
    conv = conv_bn_layer(input, 64, 7, stride=2, act="relu", name="conv1",
                         is_test=is_test)
    conv = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1,
                         pool_type="max")
    num_filters = [64, 128, 256, 512]
    for stage, count in enumerate(counts):
        stride = 2 if stage != 0 else 1
        conv = block_fn(conv, num_filters[stage], stride,
                        name="res%d_0" % (stage + 2), is_test=is_test)
        if scan_stages and count > 1:
            conv = _scan_stage_tail(conv, count - 1, num_filters[stage],
                                    "res%d_scan" % (stage + 2),
                                    is_test=is_test)
        else:
            for blk in range(1, count):
                conv = block_fn(conv, num_filters[stage], 1,
                                name="res%d_%d" % (stage + 2, blk),
                                is_test=is_test)
    pool = layers.pool2d(conv, pool_type="avg", global_pooling=True)
    import math

    stdv = 1.0 / math.sqrt(pool.shape[1] * 1.0)
    return layers.fc(
        input=pool, size=class_dim,
        param_attr=ParamAttr(
            name="fc_weights",
            initializer=fluid.initializer.Uniform(-stdv, stdv)),
        bias_attr=ParamAttr(name="fc_offset"))


def build_resnet_train(image_shape=(3, 224, 224), class_dim=1000, depth=50,
                       lr=0.1, momentum=0.9, weight_decay=1e-4,
                       is_test=False, scan_stages=False):
    """Full training program: returns (loss, acc, feeds)."""
    img = layers.data(name="image", shape=list(image_shape),
                      dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    logits = resnet(img, class_dim=class_dim, depth=depth,
                    is_test=is_test, scan_stages=scan_stages)
    loss = layers.softmax_with_cross_entropy(logits, label)
    avg_loss = layers.mean(loss)
    acc = layers.accuracy(input=layers.softmax(logits), label=label)
    if not is_test:
        opt = fluid.optimizer.MomentumOptimizer(
            learning_rate=lr, momentum=momentum,
            regularization=fluid.regularizer.L2Decay(weight_decay))
        opt.minimize(avg_loss)
    return avg_loss, acc, ["image", "label"]
