"""ResNet for ImageNet classification (BASELINE.json config 2).

Built with the fluid static-graph layers API the way reference users do
(cf. the model zoo style used by `tests/book` and `dist_se_resnext.py`
in `python/paddle/fluid/tests/unittests/`): conv2d + batch_norm + pool2d
bottleneck stacks. On TPU the whole train step lowers to one XLA
computation; convs hit the MXU via lax.conv_general_dilated.
"""
from __future__ import annotations

from .. import fluid
from ..fluid import layers
from ..fluid.param_attr import ParamAttr

DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, name=None, is_test=False):
    conv = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        act=None, bias_attr=False,
        param_attr=ParamAttr(name=name + "_weights" if name else None))
    return layers.batch_norm(
        input=conv, act=act, is_test=is_test,
        param_attr=ParamAttr(name=name + "_bn_scale" if name else None),
        bias_attr=ParamAttr(name=name + "_bn_offset" if name else None),
        moving_mean_name=name + "_bn_mean" if name else None,
        moving_variance_name=name + "_bn_var" if name else None)


def shortcut(input, ch_out, stride, name, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, name=name,
                             is_test=is_test)
    return input


def bottleneck_block(input, num_filters, stride, name, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu",
                          name=name + "_branch2a", is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride, act="relu",
                          name=name + "_branch2b", is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, act=None,
                          name=name + "_branch2c", is_test=is_test)
    short = shortcut(input, num_filters * 4, stride,
                     name=name + "_branch1", is_test=is_test)
    return layers.relu(layers.elementwise_add(short, conv2))


def basic_block(input, num_filters, stride, name, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 3, stride=stride, act="relu",
                          name=name + "_branch2a", is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, act=None,
                          name=name + "_branch2b", is_test=is_test)
    short = shortcut(input, num_filters, stride, name=name + "_branch1",
                     is_test=is_test)
    return layers.relu(layers.elementwise_add(short, conv1))


def resnet(input, class_dim=1000, depth=50, is_test=False):
    """Build the logits head over `input` (NCHW float)."""
    block_type, counts = DEPTH_CFG[depth]
    block_fn = bottleneck_block if block_type == "bottleneck" \
        else basic_block
    conv = conv_bn_layer(input, 64, 7, stride=2, act="relu", name="conv1",
                         is_test=is_test)
    conv = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1,
                         pool_type="max")
    num_filters = [64, 128, 256, 512]
    for stage, count in enumerate(counts):
        for blk in range(count):
            stride = 2 if blk == 0 and stage != 0 else 1
            conv = block_fn(conv, num_filters[stage], stride,
                            name="res%d_%d" % (stage + 2, blk),
                            is_test=is_test)
    pool = layers.pool2d(conv, pool_type="avg", global_pooling=True)
    import math

    stdv = 1.0 / math.sqrt(pool.shape[1] * 1.0)
    return layers.fc(
        input=pool, size=class_dim,
        param_attr=ParamAttr(
            name="fc_weights",
            initializer=fluid.initializer.Uniform(-stdv, stdv)),
        bias_attr=ParamAttr(name="fc_offset"))


def build_resnet_train(image_shape=(3, 224, 224), class_dim=1000, depth=50,
                       lr=0.1, momentum=0.9, weight_decay=1e-4,
                       is_test=False):
    """Full training program: returns (loss, acc, feeds)."""
    img = layers.data(name="image", shape=list(image_shape),
                      dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    logits = resnet(img, class_dim=class_dim, depth=depth, is_test=is_test)
    loss = layers.softmax_with_cross_entropy(logits, label)
    avg_loss = layers.mean(loss)
    acc = layers.accuracy(input=layers.softmax(logits), label=label)
    if not is_test:
        opt = fluid.optimizer.MomentumOptimizer(
            learning_rate=lr, momentum=momentum,
            regularization=fluid.regularizer.L2Decay(weight_decay))
        opt.minimize(avg_loss)
    return avg_loss, acc, ["image", "label"]
