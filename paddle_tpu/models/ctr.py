"""CTR / recommender models — the sparse-embedding workload family.

Two classic click-through-rate architectures over categorical slot ids
plus dense features (reference: PaddleRec's wide_deep and the DLRM
interaction idiom; the paper's Downpour-style "millions of users"
workload class):

- ``wide_deep``: a linear "wide" head over per-slot 1-d embeddings
  plus a "deep" MLP over the concatenated slot embeddings and dense
  features (Cheng et al. 2016).
- ``dlrm_tiny``: bottom MLP over dense features, pairwise dot-product
  interaction between the slot embeddings and the bottom output, top
  MLP over [bottom, interactions] (Naumann et al. 2019, scaled to
  tier-1 size).

Every slot embedding is built with ``is_sparse=True`` so the
vocab-sharded engine (paddle_tpu/embedding) plans it on data-parallel
meshes: tables shard P(ici) on the vocab axis, lookups lower to
all_gather(ids) -> mask-local-gather -> one psum_scatter, and the
backward applies row-sparse updates on the owning shard — a second
model family with a fundamentally different comm signature from
BERT/ResNet (collective bytes ∝ touched rows, not params).
"""
from __future__ import annotations

import numpy as np

from .. import fluid
from ..fluid import layers


class CTRConfig:
    """Tiny tier-1 defaults; scale vocab_sizes up for bench runs."""

    def __init__(self, vocab_sizes=(200, 120, 80, 50), embed_dim=8,
                 dense_dim=4, hidden=(32, 16), arch="wide_deep",
                 padding_idx=0):
        self.vocab_sizes = tuple(int(v) for v in vocab_sizes)
        self.embed_dim = int(embed_dim)
        self.dense_dim = int(dense_dim)
        self.hidden = tuple(int(h) for h in hidden)
        self.arch = arch
        self.padding_idx = padding_idx

    @property
    def slot_names(self):
        return ["slot_%d" % i for i in range(len(self.vocab_sizes))]

    @property
    def feed_names(self):
        return self.slot_names + ["dense", "label"]


def _inputs(cfg: CTRConfig):
    slots = [layers.data(name=n, shape=[1], dtype="int64")
             for n in cfg.slot_names]
    dense = layers.data(name="dense", shape=[cfg.dense_dim],
                        dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    return slots, dense, label


def _slot_embeddings(cfg: CTRConfig, slots, dim, prefix):
    embs = []
    for i, (s, v) in enumerate(zip(slots, cfg.vocab_sizes)):
        embs.append(layers.embedding(
            s, size=[v, dim], is_sparse=True,
            padding_idx=cfg.padding_idx,
            param_attr=fluid.ParamAttr(name="%s_emb_%d" % (prefix, i))))
    return embs


def build_ctr_train(cfg: CTRConfig = None, lr=0.05, optimizer="adagrad"):
    """Build the train program in the CURRENT default programs.
    Returns (loss, auc_input_sigmoid, feed_names)."""
    cfg = cfg or CTRConfig()
    slots, dense, label = _inputs(cfg)
    if cfg.arch == "dlrm_tiny":
        embs = _slot_embeddings(cfg, slots, cfg.embed_dim, "dlrm")
        bot = dense
        for h in cfg.hidden:
            bot = layers.fc(input=bot, size=h, act="relu")
        bot = layers.fc(input=bot, size=cfg.embed_dim, act="relu")
        feats = embs + [bot]
        # pairwise dot interactions (the DLRM second-order term)
        inter = []
        for i in range(len(feats)):
            for j in range(i + 1, len(feats)):
                inter.append(layers.reduce_sum(
                    feats[i] * feats[j], dim=1, keep_dim=True))
        top = layers.concat([bot] + inter, axis=1)
        for h in cfg.hidden:
            top = layers.fc(input=top, size=h, act="relu")
        logit = layers.fc(input=top, size=1)
    else:  # wide_deep
        wide_embs = _slot_embeddings(cfg, slots, 1, "wide")
        deep_embs = _slot_embeddings(cfg, slots, cfg.embed_dim, "deep")
        wide = layers.concat(wide_embs + [dense], axis=1)
        wide_logit = layers.fc(input=wide, size=1)
        deep = layers.concat(deep_embs + [dense], axis=1)
        for h in cfg.hidden:
            deep = layers.fc(input=deep, size=h, act="relu")
        deep_logit = layers.fc(input=deep, size=1)
        logit = wide_logit + deep_logit
    labelf = layers.cast(label, "float32")
    loss = layers.mean(layers.sigmoid_cross_entropy_with_logits(
        logit, labelf))
    prob = layers.sigmoid(logit)
    O = fluid.optimizer
    opt = {"sgd": lambda: O.SGDOptimizer(learning_rate=lr),
           "adagrad": lambda: O.AdagradOptimizer(learning_rate=lr),
           "adam": lambda: O.AdamOptimizer(learning_rate=lr),
           }[optimizer]()
    opt.minimize(loss)
    return loss, prob, cfg.feed_names


def synthetic_batch(cfg: CTRConfig, batch, seed=0, zipf=1.3):
    """One synthetic CTR batch: Zipf-skewed slot ids (recommender id
    popularity is long-tailed — the skew is what gives the cold tier
    a working set), uniform dense features, and a label correlated
    with the ids so training actually reduces loss."""
    r = np.random.RandomState(seed)
    feed = {}
    score = np.zeros((batch,), np.float64)
    for name, v in zip(cfg.slot_names, cfg.vocab_sizes):
        ids = r.zipf(zipf, size=(batch,)) % (v - 1) + 1  # skip padding 0
        feed[name] = ids.reshape(batch, 1).astype("int64")
        score += (ids % 7) / 7.0
    feed["dense"] = r.rand(batch, cfg.dense_dim).astype("float32")
    score = score / len(cfg.vocab_sizes) + 0.2 * r.randn(batch)
    feed["label"] = (score > np.median(score)).astype(
        "int64").reshape(batch, 1)
    return feed
