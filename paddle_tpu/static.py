"""paddle.static 2.0-style namespace (reference: the 2.0 re-export of the
fluid static-graph API)."""
from .fluid.framework import (  # noqa: F401
    Program, program_guard, default_main_program,
    default_startup_program, name_scope,
)
from .fluid.executor import Executor  # noqa: F401
from .fluid.compiler import CompiledProgram  # noqa: F401
from .fluid.backward import append_backward, gradients  # noqa: F401
from .fluid.io import (  # noqa: F401
    save_inference_model, load_inference_model, save, load,
)
from .fluid.layers.tensor import data  # noqa: F401
from .fluid import nets  # noqa: F401
from . import nn  # noqa: F401
