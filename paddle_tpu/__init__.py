"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities (reference: ZhouFengMing03/Paddle, see SURVEY.md), built from
scratch on JAX/XLA idioms: programs lower to single jitted XLA computations,
collectives ride ICI via mesh axes, autodiff is jax.vjp.

Import as a drop-in `paddle` namespace:
    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
"""
__version__ = "0.1.0"

from . import ops  # noqa: F401  (registers all operators)
from . import fluid  # noqa: F401
from .core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace, XPUPlace,
)
from .fluid.framework import (  # noqa: F401
    Program, program_guard, default_main_program, default_startup_program,
    in_dygraph_mode, name_scope, cpu_places, cuda_places, tpu_places,
    is_compiled_with_cuda, is_compiled_with_tpu,
)
from .fluid.executor import Executor  # noqa: F401
from .fluid.param_attr import ParamAttr  # noqa: F401
from .fluid.dygraph.base import (  # noqa: F401
    to_variable, no_grad, grad, enable_dygraph, disable_dygraph,
)
from .fluid.dygraph.base import Tensor  # noqa: F401
from .fluid import initializer  # noqa: F401
from .fluid import regularizer  # noqa: F401
from .fluid import metrics  # noqa: F401

from . import distributed  # noqa: F401
from . import observability  # noqa: F401
from . import framework  # noqa: F401
from . import imperative  # noqa: F401
from . import metric  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import compat  # noqa: F401
from . import sysconfig  # noqa: F401
from . import static  # noqa: F401
from . import jit  # noqa: F401
from .batch import batch  # noqa: F401
from . import fleet  # noqa: F401
from .incubate import complex  # noqa: F401
from .framework.random import manual_seed  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401
from . import parallel  # noqa: F401
from . import nn  # noqa: F401
from . import tensor  # noqa: F401
from . import optimizer  # noqa: F401
from . import models  # noqa: F401
from . import hapi  # noqa: F401
from . import incubate  # noqa: F401
from .hapi import Model  # noqa: F401
from .hapi.model import Input as static_Input  # noqa: F401

# 2.0 functional surface: paddle.add / paddle.matmul / ... (reference:
# python/paddle/__init__.py re-exporting paddle.tensor)
from .tensor import (  # noqa: F401
    add, subtract, multiply, divide, floor_divide, mod, remainder, pow,
    maximum, minimum, sqrt, rsqrt, square, abs, sign, ceil, floor, round,
    reciprocal, exp, log, log2, log10, log1p, sin, cos, tan, asin, acos,
    atan, sinh, cosh, tanh, erf, sum, mean, max, min, prod, all, any,
    cumsum, clip, isnan, isinf, isfinite, add_n, increment, scale, stanh,
    matmul, bmm, dot, norm, t, dist, var, std,
    zeros, ones, full, zeros_like, ones_like, full_like, arange, linspace,
    eye, diag, meshgrid, tril, triu, clone, empty, numel,
    reshape, transpose, concat, stack, unstack, split, chunk, squeeze,
    unsqueeze, flatten, flip, roll, tile, expand, broadcast_to, expand_as,
    gather, gather_nd, scatter, scatter_nd_add, slice, strided_slice,
    cast, unique, take_along_axis,
    equal, not_equal, less_than, less_equal, greater_than, greater_equal,
    logical_and, logical_or, logical_xor, logical_not, equal_all, allclose,
    argmax, argmin, argsort, sort, topk, where, nonzero, index_select,
    masked_select,
)
from .tensor.random import (  # noqa: F401
    uniform, normal, rand, randn, randint, randperm, bernoulli,
    multinomial,
)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    import numpy as np

    from .core.types import to_numpy_dtype
    from .fluid.dygraph.base import Tensor as _T

    arr = np.asarray(data)
    if dtype is not None:
        arr = arr.astype(to_numpy_dtype(dtype))
    return _T(arr, stop_gradient=stop_gradient)


def seed(value):
    import numpy as np

    np.random.seed(value)
    default_main_program().random_seed = value
    default_startup_program().random_seed = value
    return value


def set_device(device):
    return device


def get_device():
    import jax

    return jax.default_backend()


# fluid-style save/load at top level (2.0 API surface)
from .fluid.dygraph.checkpoint import (  # noqa: F401,E402
    save_dygraph, load_dygraph,
)
from .fluid.io import save, load  # noqa: F401,E402
