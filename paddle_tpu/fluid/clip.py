"""Gradient clipping (reference: `python/paddle/fluid/clip.py`)."""
from __future__ import annotations

from typing import List


class BaseGradientClipAttr:
    def __call__(self, params_grads):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        from .framework import in_dygraph_mode

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            if in_dygraph_mode():
                from .dygraph import base as dy_base

                ng = dy_base.raw_op("clip", {"X": [g._value()]},
                                    {"min": self.min, "max": self.max},
                                    ["Out"])[0]
                out.append((p, dy_base.wrap_raw(ng)))
            else:
                g.block.append_op(type="clip", inputs={"X": [g]},
                                  outputs={"Out": [g]},
                                  attrs={"min": self.min, "max": self.max})
                out.append((p, g))
        return out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        from .framework import in_dygraph_mode

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            if in_dygraph_mode():
                from .dygraph import base as dy_base

                ng = dy_base.raw_op("clip_by_norm", {"X": [g._value()]},
                                    {"max_norm": self.clip_norm}, ["Out"])[0]
                out.append((p, dy_base.wrap_raw(ng)))
            else:
                g.block.append_op(type="clip_by_norm", inputs={"X": [g]},
                                  outputs={"Out": [g]},
                                  attrs={"max_norm": self.clip_norm})
                out.append((p, g))
        return out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        from .framework import in_dygraph_mode

        if in_dygraph_mode():
            return self._eager(params_grads)
        return self._static(params_grads)

    def _static(self, params_grads):
        from .layers import nn, tensor
        from .framework import unique_name

        grads = [g for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        block = grads[0].block
        sq_sums = []
        for g in grads:
            sq = block.create_var(name=unique_name("gsq"), shape=(1,),
                                  dtype="float32")
            block.append_op(type="squared_l2_norm", inputs={"X": [g]},
                            outputs={"Out": [sq]})
            sq_sums.append(sq)
        total = block.create_var(name=unique_name("global_norm_sq"),
                                 shape=(1,), dtype="float32")
        block.append_op(type="sum", inputs={"X": sq_sums},
                        outputs={"Out": [total]})
        gnorm = block.create_var(name=unique_name("global_norm"),
                                 shape=(1,), dtype="float32")
        block.append_op(type="sqrt", inputs={"X": [total]},
                        outputs={"Out": [gnorm]})
        clip_var = tensor.fill_constant([1], "float32", self.clip_norm)
        # scale = clip / max(gnorm, clip)
        maxed = block.create_var(name=unique_name("gn_max"), shape=(1,),
                                 dtype="float32")
        block.append_op(type="elementwise_max",
                        inputs={"X": [gnorm], "Y": [clip_var]},
                        outputs={"Out": [maxed]}, attrs={"axis": -1})
        scale = block.create_var(name=unique_name("gn_scale"), shape=(1,),
                                 dtype="float32")
        block.append_op(type="elementwise_div",
                        inputs={"X": [clip_var], "Y": [maxed]},
                        outputs={"Out": [scale]}, attrs={"axis": -1})
        for p, g in params_grads:
            if g is None:
                continue
            block.append_op(type="elementwise_mul",
                            inputs={"X": [g], "Y": [scale]},
                            outputs={"Out": [g]}, attrs={"axis": -1})
        return params_grads

    def _eager(self, params_grads):
        import jax.numpy as jnp

        from .dygraph import base as dy_base

        grads = [(p, g) for p, g in params_grads if g is not None]
        total = sum(float(jnp.sum(jnp.square(
            g._value().astype(jnp.float32)))) for _, g in grads)
        gnorm = total ** 0.5
        scale = self.clip_norm / max(gnorm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, dy_base.wrap_raw(g._value() * scale)))
        return out


_clip_attr = {}


def set_gradient_clip(clip, param_list=None, program=None):
    _clip_attr["default"] = clip


def append_gradient_clip_ops(params_grads):
    clip = _clip_attr.get("default")
    per_param = any(getattr(p, "gradient_clip_attr", None) is not None
                    for p, _ in params_grads)
    if clip is None and not per_param:
        return params_grads
    if clip is not None:
        return clip(params_grads)
    out = []
    for p, g in params_grads:
        attr = getattr(p, "gradient_clip_attr", None)
        if attr is not None and g is not None:
            out.extend(attr([(p, g)]))
        else:
            out.append((p, g))
    return out


ErrorClipByValue = GradientClipByValue
