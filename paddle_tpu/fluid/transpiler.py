"""DistributeTranspiler: parameter-server program rewrite.

Reference parity: `python/paddle/fluid/transpiler/distribute_transpiler.py`
(:256 class, :545 transpile) — params are assigned across pservers, the
trainer's optimizer ops move into per-param blocks of a pserver program
executed by `listen_and_serv` (`operators/distributed_ops/
listen_and_serv_op.cc:336`), and the trainer pushes grads / pulls params
through send/recv ops driven by a Communicator
(`operators/distributed/communicator.h:176-395`).

TPU-native split: the dense fwd/bwd stays ONE jitted XLA computation on
the accelerator; the PS tier is host machinery — a TCP RPC server
(distributed/rpc.py) holding the tables, applying the REAL optimizer ops
by running the transpiled pserver program through the normal fluid
Executor. send/recv/barrier ops appear in the trainer program for API
parity but lower to no-ops inside jit; the host-side PSCommunicator
(distributed/ps.py) performs the actual push/pull around each step.

Modes (reference DistributedMode): sync (barrier-aggregated grads, one
update per global step), async (grads applied on arrival), geo (trainers
push param deltas every k local steps).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from . import framework
from .framework import Operator, Variable, grad_var_name


class DistributeTranspilerConfig:
    """Reference: transpiler/distribute_transpiler.py
    DistributeTranspilerConfig. slice_var_up is accepted but the TPU build
    assigns whole vars round-robin (no block slicing — PJRT hosts don't
    need balanced message sizes the way gRPC did)."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192
        self.sync_mode = True
        self.runtime_split_send_recv = False
        self.geo_sgd_mode = False
        self.geo_sgd_need_push_nums = 100
        self.completely_not_async = False
        # half-async communicator (reference: communicator.h:299
        # HalfAsyncCommunicator): trainers enqueue grads and continue;
        # a background thread merges + batch-sends and pulls params back
        self.half_async = False
        self.mode = "pserver"
        self.print_log = False
        self.wait_port = True


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._param_endpoint: Dict[str, str] = {}
        self._opt_ops_per_param: Dict[str, Operator] = {}
        self._lr_and_aux_vars: List[str] = []
        self._origin_program = None
        self._origin_startup = None
        self._trainer_id = 0
        self._trainers = 1
        self._eplist: List[str] = []
        self._mode = "sync"

    # -- public API (reference :545) --------------------------------------
    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint="127.0.0.1:6174"):
        program = program or framework.default_main_program()
        startup_program = startup_program or \
            framework.default_startup_program()
        self._origin_program = program
        self._origin_startup = startup_program
        self._trainer_id = int(trainer_id)
        self._trainers = int(trainers)
        self._eplist = [e.strip() for e in pservers.split(",") if e.strip()]
        if self.config.geo_sgd_mode:
            self._mode = "geo"
        elif self.config.half_async:
            self._mode = "half_async"
        elif sync_mode:
            self._mode = "sync"
        else:
            self._mode = "async"

        block = program.global_block()
        bops = [op for op in block.ops if op.type == "backward"]
        if not bops:
            raise ValueError("transpile() needs a program with a backward "
                             "section (run optimizer.minimize first)")

        # optimizer ops: post-backward ops updating a Param input slot
        bwd_idx = block.ops.index(bops[0])
        opt_ops = []
        for op in block.ops[bwd_idx + 1:]:
            if "Param" in op.input_names and op.input_names["Param"]:
                opt_ops.append(op)

        # round-robin whole-var placement (reference RoundRobin splitter)
        for i, op in enumerate(opt_ops):
            pname = op.input_names["Param"][0]
            self._param_endpoint[pname] = self._eplist[i % len(self._eplist)]
            self._opt_ops_per_param[pname] = op

        # aux vars the pserver update needs (lr, accumulators, ...): every
        # persistable non-param input of the optimizer ops
        aux = []
        for op in opt_ops:
            for slot, names in op.input_names.items():
                if slot in ("Param", "Grad"):
                    continue
                for n in names:
                    v = block._find_var_recursive(n)
                    if v is not None and v.persistable and n not in aux:
                        aux.append(n)
        self._lr_and_aux_vars = aux

        # sparse distributed tables: lookup_table ops with
        # is_distributed=True prefetch rows from the pserver instead of
        # holding/pulling the dense table (reference:
        # operators/distributed_ops/distributed_lookup_table_op.cc +
        # distributed/parameter_prefetch.cc)
        self._sparse_tables = {}
        if self._mode in ("sync", "async", "half_async"):
            self._rewrite_sparse_lookups(block, bops[0])

        # trainer rewrite: optimizer ops for remote params are replaced by
        # send/recv markers (no-ops under jit; the PSCommunicator does the
        # host RPC around each step)
        if self._mode == "geo":
            # geo: trainers keep optimizing locally; only the periodic
            # delta push is added, so optimizer ops stay
            pass
        else:
            for op in opt_ops:
                block.ops.remove(op)
        for pname, op in self._opt_ops_per_param.items():
            gname = op.input_names["Grad"][0]
            block.append_op(
                type="send", inputs={"X": [gname]}, outputs={},
                attrs={"endpoints": [self._param_endpoint[pname]],
                       "sync_mode": self._mode == "sync"})
        if self._mode == "sync":
            block.append_op(type="send_barrier", inputs={}, outputs={},
                            attrs={"endpoints": self._eplist})
        for pname in self._opt_ops_per_param:
            block.append_op(
                type="recv", inputs={}, outputs={"Out": [pname]},
                attrs={"epmap": [self._param_endpoint[pname]]})
        if self._mode == "sync":
            block.append_op(type="fetch_barrier", inputs={}, outputs={},
                            attrs={"endpoints": self._eplist})

        program._ps_cfg = {
            "mode": self._mode,
            "trainer_id": self._trainer_id,
            "trainers": self._trainers,
            "param_endpoint": dict(self._param_endpoint),
            "grad_of": {self._opt_ops_per_param[p].input_names["Grad"][0]:
                        p for p in self._opt_ops_per_param},
            "geo_push_every": self.config.geo_sgd_need_push_nums
            if self._mode == "geo" else 0,
            "sparse_tables": dict(self._sparse_tables),
        }
        program._version += 1

    def _rewrite_sparse_lookups(self, block, bop):
        """Rewrite `lookup_table(is_distributed=True)` into a prefetch
        gather: the executor fetches the step's unique rows from the
        pserver into a fixed-size PREFETCH feed, the op gathers with
        host-remapped ids, and the prefetch grad rows are pushed back
        sparsely (SelectedRows over DCN — never the dense table)."""
        for op in list(block.ops):
            if op.type not in ("lookup_table", "lookup_table_v2"):
                continue
            if not op.attrs.get("is_distributed"):
                continue
            wname = op.input_names["W"][0]
            ids_name = op.input_names["Ids"][0]
            if wname not in self._param_endpoint:
                continue
            wvar = block._find_var_recursive(wname)
            ids_var = block._find_var_recursive(ids_name)
            # one prefetch slot per id in the batch (duplicates padded);
            # the batch dim is dynamic, so the actual extent comes from
            # the runtime feed (communicator pads unique rows up to it)
            prefetch = block.create_var(
                name=wname + "@PREFETCH",
                shape=[-1, wvar.shape[-1]], dtype=wvar.dtype,
                persistable=False, stop_gradient=False)
            remap = block.create_var(
                name=ids_name + "@REMAP", shape=list(ids_var.shape),
                dtype="int64", persistable=False, stop_gradient=True)
            op.input_names["W"] = [prefetch.name]
            op.input_names["Ids"] = [remap.name]
            # grad of the prefetch rows = the sparse push payload
            bop.attrs.setdefault("diff_names", []).append(prefetch.name)
            bop.output_names.setdefault("Grad", []).append(
                grad_var_name(prefetch.name))
            block.create_var(name=grad_var_name(prefetch.name),
                             shape=prefetch.shape, dtype=prefetch.dtype,
                             stop_gradient=True)
            # lr for the server-side sparse sgd: the removed optimizer
            # op's LearningRate initial value
            opt_op = self._opt_ops_per_param[wname]
            lr_name = opt_op.input_names.get("LearningRate", [None])[0]
            lr_val = self._startup_const_value(lr_name)
            self._sparse_tables[wname] = {
                "endpoint": self._param_endpoint[wname],
                "ids_feed": ids_name,
                "prefetch": prefetch.name,
                "remap": remap.name,
                "grad": grad_var_name(prefetch.name),
                "lr": lr_val if lr_val is not None else 1.0,
            }
            # the table itself is no longer a dense send/recv param
            del self._param_endpoint[wname]
            del self._opt_ops_per_param[wname]

    def _startup_const_value(self, name):
        if name is None:
            return None
        for op in self._origin_startup.global_block().ops:
            if name in op.output_arg_names and "value" in op.attrs:
                return float(op.attrs["value"])
        return None

    def get_trainer_program(self, wait_port=True):
        return self._origin_program

    def get_pserver_program(self, endpoint):
        """Per-endpoint update program: param/grad/aux vars + the original
        optimizer ops for params hosted here (reference builds
        listen_and_serv with per-param sub-blocks; here the whole update
        is one block executed per aggregated step)."""
        prog = framework.Program()
        pblock = prog.global_block()
        src_block = self._origin_program.global_block()

        hosted = [p for p, ep in self._param_endpoint.items()
                  if ep == endpoint]
        sparse_here = {w: meta for w, meta in self._sparse_tables.items()
                       if meta["endpoint"] == endpoint}
        for wname in sparse_here:
            v = src_block._find_var_recursive(wname)
            pblock.create_var(name=wname, shape=v.shape, dtype=v.dtype,
                              persistable=True, stop_gradient=True)
        prog._ps_sparse = {w: m["lr"] for w, m in sparse_here.items()}
        needed_vars = set()
        for pname in hosted:
            op = self._opt_ops_per_param[pname]
            for names in list(op.input_names.values()) + \
                    list(op.output_names.values()):
                needed_vars.update(names)
        for n in sorted(needed_vars):
            v = src_block._find_var_recursive(n)
            if v is None:
                continue
            pblock.create_var(
                name=n, shape=v.shape, dtype=v.dtype,
                persistable=v.persistable, stop_gradient=True)
        for pname in hosted:
            op = self._opt_ops_per_param[pname]
            pblock.append_op(type=op.type,
                             inputs={s: list(ns) for s, ns
                                     in op.input_names.items()},
                             outputs={s: list(ns) for s, ns
                                      in op.output_names.items()},
                             attrs=dict(op.attrs))
        prog._ps_hosted_params = hosted + sorted(sparse_here)
        prog._ps_grad_of = {self._opt_ops_per_param[p].input_names
                            ["Grad"][0]: p for p in hosted}
        return prog

    def get_startup_program(self, endpoint, pserver_program=None):
        """Init ops (fill_constant/gaussian/...) for vars hosted on this
        endpoint, copied from the original startup program."""
        hosted = set(p for p, ep in self._param_endpoint.items()
                     if ep == endpoint)
        hosted |= {w for w, m in self._sparse_tables.items()
                   if m["endpoint"] == endpoint}
        hosted |= set(self._lr_and_aux_vars)
        prog = framework.Program()
        pblock = prog.global_block()
        src = self._origin_startup.global_block()
        for op in src.ops:
            outs = op.output_arg_names
            if not outs or not all(o in hosted for o in outs):
                continue
            for n in set(op.input_arg_names) | set(outs):
                if pblock._find_var_recursive(n) is None:
                    v = src._find_var_recursive(n)
                    if v is not None:
                        pblock.create_var(name=n, shape=v.shape,
                                          dtype=v.dtype, persistable=True)
            pblock.append_op(type=op.type,
                             inputs={s: list(ns) for s, ns
                                     in op.input_names.items()},
                             outputs={s: list(ns) for s, ns
                                      in op.output_names.items()},
                             attrs=dict(op.attrs))
        return prog


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    """Deprecated no-op (reference:
    `transpiler/memory_optimization_transpiler.py:18` — already a
    warn-and-return there; buffer reuse is owned by the runtime, here by
    XLA's buffer assignment + donation)."""
    import warnings

    warnings.warn(
        "paddle_tpu.fluid.memory_optimize is deprecated and does "
        "nothing: XLA buffer assignment (plus executor donation) owns "
        "memory reuse.", DeprecationWarning, stacklevel=2)


def release_memory(input_program, skip_opt_set=None):
    """Deprecated no-op twin of memory_optimize (reference:
    `memory_optimization_transpiler.py:44`)."""
    import warnings

    warnings.warn(
        "paddle_tpu.fluid.release_memory is deprecated and does "
        "nothing.", DeprecationWarning, stacklevel=2)
