"""Graph-building front end: Program / Block / Operator / Variable.

Reference parity: `python/paddle/fluid/framework.py` — `Program`
(`framework.py:3852`), `Block` (`:2391`), `Operator` (`:1822`), `Variable`
(`:835`), default program globals (`:180-246`), unique_name. The IR here is
the same ProgramDesc shape (blocks of ops over named vars) but lowering
happens per-block into ONE jitted XLA computation (see lowering.py) instead
of an op-by-op C++ executor loop — the op loop at `executor.cc:471` is the
unit the TPU design replaces (SURVEY.md §3A).

Shape inference runs through `jax.eval_shape` on each op's jax compute
function at `append_op` time (replacing per-op InferShape).
"""
from __future__ import annotations

import collections
import contextlib
from typing import Dict, List, Optional

import numpy as np

from ..core import types as core_types
from ..core.place import (  # noqa: F401  (re-exported)
    CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace, Place,
    _current_expected_place,
)

# ---------------------------------------------------------------------------
# unique_name (reference: python/paddle/fluid/unique_name.py)
# ---------------------------------------------------------------------------


class _UniqueNameGenerator:
    def __init__(self, prefix=None):
        self.ids = collections.defaultdict(int)
        self.prefix = prefix or ""

    def __call__(self, key):
        tmp = self.ids[key]
        self.ids[key] += 1
        return self.prefix + "_".join([key, str(tmp)])


_name_generator = _UniqueNameGenerator()


def unique_name(key: str) -> str:
    return _name_generator(key)


@contextlib.contextmanager
def unique_name_guard(prefix: str = ""):
    global _name_generator
    old = _name_generator
    _name_generator = _UniqueNameGenerator(prefix)
    try:
        yield
    finally:
        _name_generator = old


GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


def require_version(min_version, max_version=None):
    """Raise unless the installed framework version is within
    [min_version, max_version] (max_version None = no upper bound).
    Reference: `python/paddle/fluid/framework.py:73`. Version strings
    are dotted integers, short forms zero-extended ('1.4' == '1.4.0')."""
    if not isinstance(min_version, str):
        raise TypeError("min_version must be str, got %s"
                        % type(min_version))
    if not isinstance(max_version, (str, type(None))):
        raise TypeError("max_version must be str or None, got %s"
                        % type(max_version))

    def parse(v):
        parts = v.split(".")
        if not parts or not all(p.isdigit() for p in parts):
            raise ValueError(
                "version must be dotted integers like '1.4.0', got %r"
                % v)
        nums = [int(p) for p in parts]
        return tuple(nums + [0] * (4 - len(nums)))

    from .. import __version__

    installed = parse(__version__)
    if installed < parse(min_version):
        raise Exception(
            "installed version %s is below the required minimum %s"
            % (__version__, min_version))
    if max_version is not None and installed > parse(max_version):
        raise Exception(
            "installed version %s is above the required maximum %s"
            % (__version__, max_version))


def is_compiled_with_cuda() -> bool:
    """Always False: this build targets TPU via XLA (reference:
    `framework.py:151`); scripts use it to pick CUDAPlace vs CPUPlace."""
    return False


def load_op_library(lib_filename):
    """Load a shared library of custom operators (reference:
    `framework.py:5395` loads a .so of REGISTER_OPERATOR ops). Here
    custom op *kernels* are Python entries in the op registry
    (paddle_tpu.ops.register_op); a native .so may still carry
    C-ABI helpers, which this loads via ctypes. The library's
    `paddle_tpu_register_ops` hook is invoked when exported."""
    import ctypes

    lib = ctypes.CDLL(lib_filename)
    hook = getattr(lib, "paddle_tpu_register_ops", None)
    if hook is not None:
        hook()
    return lib


class ComplexVariable:
    """Pair of real/imag Variables — the reference's dygraph-only
    complex-number carrier (`framework.py:1691`). Arithmetic composes
    the underlying ops; kept minimal (the TPU-native path represents
    complex data as paired reals end to end)."""

    def __init__(self, real, imag):
        self.real = real
        self.imag = imag

    @property
    def shape(self):
        return self.real.shape

    @property
    def dtype(self):
        return self.real.dtype

    def numpy(self):
        import numpy as np

        return (np.asarray(self.real.numpy())
                + 1j * np.asarray(self.imag.numpy()))

    def __repr__(self):
        return "ComplexVariable(real=%r, imag=%r)" % (self.real,
                                                      self.imag)


# ---------------------------------------------------------------------------
# dygraph mode switch (reference: framework.py:180-246)
# ---------------------------------------------------------------------------

_dygraph_tracer_ = None


def in_dygraph_mode() -> bool:
    return _dygraph_tracer_ is not None


def _dygraph_tracer():
    return _dygraph_tracer_


def _switch_tracer(tracer):
    global _dygraph_tracer_
    old = _dygraph_tracer_
    _dygraph_tracer_ = tracer
    return old


@contextlib.contextmanager
def dygraph_guard_if_declarative():
    yield


# ---------------------------------------------------------------------------
# Variable
# ---------------------------------------------------------------------------

class Variable:
    """Symbolic variable in a Block (reference: framework.py:835)."""

    def __init__(self, block, name=None, shape=None, dtype="float32",
                 persistable=False, stop_gradient=False, is_data=False,
                 trainable=True, type=None, lod_level=0, **kwargs):
        self.block = block
        self.name = name or unique_name("_generated_var")
        self.shape = tuple(shape) if shape is not None else ()
        self.dtype = core_types.normalize_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.trainable = trainable
        self.type = type or "LOD_TENSOR"
        # LoD (ragged-sequence) nesting depth; sequences are padded dense
        # on TPU with offsets kept as host metadata (SURVEY.md §7 (a))
        self.lod_level = lod_level
        self.op = None  # producing Operator (set by append_op)

    # -- info --------------------------------------------------------------
    @property
    def grad_name(self):
        return grad_var_name(self.name)

    def astype(self, dtype):
        from .layers import tensor as _t

        return _t.cast(self, dtype)

    @property
    def ndim(self):
        return len(self.shape)

    def __repr__(self):
        return "Var(%s, shape=%s, dtype=%s%s)" % (
            self.name, self.shape, self.dtype,
            ", persistable" if self.persistable else "")

    __str__ = __repr__

    # -- operator sugar (static mode) --------------------------------------
    def _binary(self, other, op, reverse=False):
        from .layers import math_op_patch

        return math_op_patch.binary(self, other, op, reverse)

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", reverse=True)

    def __pow__(self, o):
        return self._binary(o, "elementwise_pow")

    def __mod__(self, o):
        return self._binary(o, "elementwise_mod")

    def __floordiv__(self, o):
        return self._binary(o, "elementwise_floordiv")

    def __neg__(self):
        from .layers import tensor as _t

        return _t.scale(self, scale=-1.0)

    def __matmul__(self, o):
        from .layers import nn as _nn

        return _nn.matmul(self, o)

    def __lt__(self, o):
        return self._binary(o, "less_than")

    def __le__(self, o):
        return self._binary(o, "less_equal")

    def __gt__(self, o):
        return self._binary(o, "greater_than")

    def __ge__(self, o):
        return self._binary(o, "greater_equal")

    def __eq__(self, o):
        if isinstance(o, Variable) or np.isscalar(o):
            return id(self) == id(o) if isinstance(o, Variable) else False
        return NotImplemented

    def __hash__(self):
        return id(self)


class Parameter(Variable):
    """Trainable persistable variable (reference: framework.py:5080)."""

    def __init__(self, block, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        self.regularizer = kwargs.pop("regularizer", None)
        self.optimize_attr = kwargs.pop("optimize_attr",
                                        {"learning_rate": 1.0})
        self.do_model_average = kwargs.pop("do_model_average", None)
        self.is_distributed = kwargs.pop("is_distributed", False)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------

def _var_name(v):
    """Var name for IR storage; unwraps SymbolicTensor (dygraph capture
    wrapper around a static Variable) so static layers accept either."""
    v = getattr(v, "_var", v)
    return v.name if isinstance(v, Variable) else v


class Operator:
    """One op in a block: type + slot->var-name maps + attrs
    (reference: framework.py:1822 / framework.proto OpDesc)."""

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        # store var NAMES (IR form); Variables resolved through block
        self.input_names: Dict[str, List[str]] = {}
        self.output_names: Dict[str, List[str]] = {}
        for slot, vs in (inputs or {}).items():
            self.input_names[slot] = [
                _var_name(v)
                for v in (vs if isinstance(vs, (list, tuple)) else [vs])]
        for slot, vs in (outputs or {}).items():
            self.output_names[slot] = [
                _var_name(v)
                for v in (vs if isinstance(vs, (list, tuple)) else [vs])]
        self.attrs = dict(attrs or {})
        # creation-site frames for error attribution (reference:
        # framework/op_call_stack.cc); cheap: top user frames only
        from ..core.errors import capture_user_callstack

        self._creation_stack = capture_user_callstack()

    def input(self, slot):
        return self.input_names.get(slot, [])

    def output(self, slot):
        return self.output_names.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for vs in self.input_names.values() for n in vs]

    @property
    def output_arg_names(self):
        return [n for vs in self.output_names.values() for n in vs]

    def attr(self, name):
        return self.attrs.get(name)

    def _set_attr(self, name, val):
        self.attrs[name] = val

    def has_attr(self, name):
        return name in self.attrs

    def __repr__(self):
        return "{%s: %s -> %s}" % (self.type, self.input_names,
                                   self.output_names)


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------

class Block:
    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = collections.OrderedDict()
        self.ops: List[Operator] = []

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    # -- vars --------------------------------------------------------------
    def create_var(self, **kwargs) -> Variable:
        name = kwargs.get("name") or unique_name("_generated_var")
        kwargs["name"] = name
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, **kwargs)
        self.vars[name] = v
        return v

    def create_parameter(self, **kwargs) -> Parameter:
        # parameters live in the top (global) block
        gb = self.program.global_block()
        name = kwargs.pop("name", None) or unique_name("_param")
        shape = kwargs.pop("shape")
        dtype = kwargs.pop("dtype", "float32")
        p = Parameter(gb, shape=shape, dtype=dtype, name=name, **kwargs)
        gb.vars[name] = p
        return p

    def var(self, name) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("var %r not found in block %d" % (name, self.idx))
        return v

    def _find_var_recursive(self, name):
        if name in self.vars:
            return self.vars[name]
        pb = self.parent_block
        return pb._find_var_recursive(name) if pb is not None else None

    def has_var(self, name):
        return self._find_var_recursive(name) is not None

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops ---------------------------------------------------------------
    def append_op(self, type=None, inputs=None, outputs=None, attrs=None,
                  stop_gradient=False) -> Operator:
        if in_dygraph_mode():
            raise RuntimeError(
                "Block.append_op called while in dygraph mode; layers must "
                "dispatch to the eager tracer")
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.append(op)
        self.program._version += 1
        self._infer_op_shapes(op, inputs or {}, outputs or {})
        for vs in (outputs or {}).values():
            for v in (vs if isinstance(vs, (list, tuple)) else [vs]):
                if isinstance(v, Variable):
                    v.op = op
                    if stop_gradient:
                        v.stop_gradient = True
        return op

    def _prepend_op(self, **kwargs):
        op = self.append_op(**kwargs)
        self.ops.insert(0, self.ops.pop())
        return op

    def _infer_op_shapes(self, op, inputs, outputs):
        from .. import ops as ops_lib

        if not ops_lib.has_op(op.type):
            return  # framework-level pseudo op (feed/fetch/backward/...)
        in_specs = {}
        for slot, vs in inputs.items():
            vs = vs if isinstance(vs, (list, tuple)) else [vs]
            specs = []
            for v in vs:
                v = getattr(v, "_var", v)
                var = v if isinstance(v, Variable) else self.var(v)
                specs.append((var.shape, var.dtype))
            in_specs[slot] = specs
        try:
            out_specs = ops_lib.infer_outputs(op.type, in_specs, op.attrs)
        except Exception:
            return  # leave declared shapes (dynamic-only ops)
        for slot, vs in outputs.items():
            vs = vs if isinstance(vs, (list, tuple)) else [vs]
            specs = out_specs.get(slot, [])
            for v, spec in zip(vs, specs):
                var = v if isinstance(v, Variable) else self.var(v)
                var.shape, var.dtype = tuple(spec[0]), spec[1]

    def __repr__(self):
        lines = ["Block(%d) {" % self.idx]
        for v in self.vars.values():
            lines.append("  " + repr(v))
        for op in self.ops:
            lines.append("  " + repr(op))
        lines.append("}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------

class Program:
    """A list of blocks; block 0 is global (reference: framework.py:3852)."""

    _uid_counter = 0

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0  # bumped on mutation; part of the compile key
        # never-reused identity for compile-cache keys (id() can alias
        # after GC; VERDICT r1 weak #7)
        Program._uid_counter += 1
        self._uid = Program._uid_counter
        self._is_test = False
        self._seed_counter = 0
        # distributed annotations (set by fleet/transpilers)
        self._data_parallel = False
        self._dp_axis = "dp"
        self._mesh = None

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def block(self, idx) -> Block:
        return self.blocks[idx]

    @property
    def num_blocks(self):
        return len(self.blocks)

    def _create_block(self, parent_idx=None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        self._version += 1
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def list_vars(self):
        for b in self.blocks:
            for v in b.vars.values():
                yield v

    def all_parameters(self):
        return self.global_block().all_parameters()

    # -- cloning -----------------------------------------------------------
    def clone(self, for_test=False) -> "Program":
        import copy

        p = Program()
        p.random_seed = self.random_seed
        p._data_parallel = self._data_parallel
        p._dp_axis = self._dp_axis
        p._mesh = self._mesh
        if getattr(self, "_amp", False):
            p._amp = self._amp
            p._amp_lists = self._amp_lists
            p._amp_dtype = getattr(self, "_amp_dtype", "bfloat16")
            if getattr(self, "_amp_master_of", None):
                p._amp_master_of = dict(self._amp_master_of)
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            for name, v in b.vars.items():
                nv = copy.copy(v)
                nv.block = nb
                nb.vars[name] = nv
            for op in b.ops:
                if for_test and op.type in ("backward",):
                    continue
                nop = Operator(nb, op.type)
                # keep the ORIGINAL creation site for error attribution
                # (rebuilding here would blame the clone() call)
                nop._creation_stack = op._creation_stack
                nop.input_names = {k: list(v)
                                   for k, v in op.input_names.items()}
                nop.output_names = {k: list(v)
                                    for k, v in op.output_names.items()}
                nop.attrs = dict(op.attrs)
                if for_test and "is_test" in _IS_TEST_OPS.get(op.type, ()):
                    nop.attrs["is_test"] = True
                nb.ops.append(nop)
            p.blocks.append(nb)
        if for_test:
            p._prune_optimizer_ops()
            p._is_test = True
        p._version = self._version
        return p

    def _prune_optimizer_ops(self):
        from .. import ops as ops_lib  # noqa: F401

        opt_types = {
            "sgd", "momentum", "adam", "adamw", "adamax", "adagrad",
            "decayed_adagrad", "adadelta", "rmsprop", "ftrl", "lamb",
            "lars_momentum", "dpsgd", "backward",
            "fused_sgd", "fused_momentum", "fused_adam",
        }
        for b in self.blocks:
            b.ops = [op for op in b.ops if op.type not in opt_types
                     and not op.attrs.get("_is_backward", False)]

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)

    __str__ = __repr__


_IS_TEST_OPS = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
    # QAT: eval/inference clones must stop mutating calibration state
    "fake_quantize_moving_average_abs_max": ("is_test",),
    "fake_quantize_dequantize_moving_average_abs_max": ("is_test",),
    "fake_quantize_range_abs_max": ("is_test",),
    "moving_average_abs_max_scale": ("is_test",),
}

# ---------------------------------------------------------------------------
# default programs + guards (reference: framework.py:5340-5470)
# ---------------------------------------------------------------------------

_main_program_ = Program()
_startup_program_ = Program()


def default_main_program() -> Program:
    return _main_program_


def default_startup_program() -> Program:
    return _startup_program_


def switch_main_program(p: Program) -> Program:
    global _main_program_
    old, _main_program_ = _main_program_, p
    return old


def switch_startup_program(p: Program) -> Program:
    global _startup_program_
    old, _startup_program_ = _startup_program_, p
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_start = None
    if startup_program is not None:
        old_start = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_start is not None:
            switch_startup_program(old_start)


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    # device placement is XLA's concern on TPU; accepted for compat
    yield


def cpu_places(device_count=None):
    return [CPUPlace()]


def cuda_places(device_ids=None):
    import jax

    n = len(jax.devices())
    ids = device_ids if device_ids is not None else range(n)
    return [CUDAPlace(i) for i in ids]


def tpu_places(device_ids=None):
    import jax

    n = len(jax.devices())
    ids = device_ids if device_ids is not None else range(n)
    return [TPUPlace(i) for i in ids]


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return True


def _global_seed_and_bump(program: Program):
    """Per-run RNG seed derivation (deterministic if program.random_seed)."""
    if program.random_seed:
        s = program.random_seed + program._seed_counter
    else:
        s = np.random.randint(0, 2**31 - 1)
    program._seed_counter += 1
    return s
