"""Checkpoint save/load + inference model export (reference:
`python/paddle/fluid/io.py:224-1669`; save/load kernels
`operators/save_op.cc`/`load_op.cc`; program pruning `framework/prune.cc`).

TPU-native: persistables are device arrays in the Scope; save pulls them to
host and writes one file per var (or a combined pickle), load device_puts
them back. Formats are numpy-based, self-describing, and sharding-agnostic.
For mesh-sharded SPMD state use
`paddle_tpu.distributed.ShardedCheckpointManager` (orbax-backed: per-shard
writes, restore lands directly in the live mesh layout).
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional

import numpy as np

from . import framework
from .framework import Program, Parameter, Variable
from ..core.scope import global_scope


def _ensure_dir(d):
    if d:
        os.makedirs(d, exist_ok=True)


def _save_dict(dirname, d, filename=None):
    _ensure_dir(dirname)
    if filename:
        with open(os.path.join(dirname, filename), "wb") as f:
            pickle.dump(d, f, protocol=2)
    else:
        for name, arr in d.items():
            arr = np.asarray(arr)
            safe = name.replace("/", "%2F")
            path = os.path.join(dirname, safe + ".npy")
            sidecar = os.path.join(dirname, safe + ".dtype")
            if arr.dtype.kind == "V":
                # ml_dtypes extension types (bf16 AMP params): the npy
                # descr degrades them to raw void on reload — store the
                # bit pattern as uintN with the true dtype in a sidecar
                np.save(path, arr.view("u%d" % arr.dtype.itemsize),
                        allow_pickle=False)
                with open(sidecar, "w") as f:
                    f.write(str(arr.dtype))
            else:
                np.save(path, arr, allow_pickle=False)
                if os.path.exists(sidecar):
                    os.remove(sidecar)


def _np_load(path):
    arr = np.load(path)
    sidecar = path[:-4] + ".dtype"
    if os.path.exists(sidecar):
        from ..core.types import to_numpy_dtype

        with open(sidecar) as f:
            arr = arr.view(to_numpy_dtype(f.read().strip()))
    return arr


def _load_dict(dirname, names=None, filename=None):
    if filename:
        with open(os.path.join(dirname, filename), "rb") as f:
            return pickle.load(f)
    out = {}
    if names is not None:
        for name in names:
            safe = name.replace("/", "%2F")
            p = os.path.join(dirname, safe + ".npy")
            if os.path.exists(p):
                out[name] = _np_load(p)
    else:
        for fn in os.listdir(dirname):
            if fn.endswith(".npy"):
                out[fn[:-4].replace("%2F", "/")] = _np_load(
                    os.path.join(dirname, fn))
    return out


def _collect(program, predicate, scope):
    from ..parallel.sharded_update import unshard_scope_value

    vals = {}
    for var in program.list_vars():
        if predicate(var):
            v = scope.find_var(var.name)
            if v is not None:
                # ZeRO-1 optimizer state is scope-resident as a flat
                # dp-sharded buffer; persist the logical-shape view
                vals[var.name] = np.asarray(
                    unshard_scope_value(program, var.name, v))
    return vals


def is_persistable(var):
    return var.persistable


def is_parameter(var):
    return isinstance(var, Parameter)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from ..parallel.sharded_update import unshard_scope_value

    program = main_program or framework.default_main_program()
    scope = global_scope()
    if vars is not None:
        d = {}
        for v in vars:
            name = v.name if isinstance(v, Variable) else v
            val = scope.find_var(name)
            if val is not None:
                d[name] = np.asarray(
                    unshard_scope_value(program, name, val))
    else:
        d = _collect(program, predicate or is_persistable, scope)
    _save_dict(dirname, d, filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import jax.numpy as jnp

    program = main_program or framework.default_main_program()
    scope = global_scope()
    if vars is not None:
        names = [v.name if isinstance(v, Variable) else v for v in vars]
    else:
        names = [v.name for v in program.list_vars()
                 if (predicate or is_persistable)(v)]
    d = _load_dict(dirname, names, filename)
    missing = [n for n in names if n not in d]
    if missing:
        raise RuntimeError("checkpoint at %r is missing vars %s"
                           % (dirname, missing))
    for n in names:
        scope.set_var(n, jnp.asarray(d[n]))


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=is_persistable,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=is_persistable,
              filename=filename)


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=is_parameter,
              filename=filename)


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=is_parameter,
              filename=filename)


# -- program-state API (reference: io.py:1605 fluid.save / :1669 load) ------

def save(program, model_path):
    scope = global_scope()
    params = _collect(program, is_parameter, scope)
    others = {k: v for k, v in _collect(program, is_persistable,
                                        scope).items() if k not in params}
    _ensure_dir(os.path.dirname(model_path) or ".")
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(params, f, protocol=2)
    with open(model_path + ".pdopt", "wb") as f:
        pickle.dump(others, f, protocol=2)
    with open(model_path + ".pdmodel", "wb") as f:
        pickle.dump(_program_to_desc(program), f, protocol=2)


def load(program, model_path, executor=None, var_list=None):
    import jax.numpy as jnp

    scope = global_scope()
    for suffix in (".pdparams", ".pdopt"):
        p = model_path + suffix
        if os.path.exists(p):
            with open(p, "rb") as f:
                d = pickle.load(f)
            for k, v in d.items():
                scope.set_var(k, jnp.asarray(v))


# -- inference model export (reference: io.py:1100) -------------------------

def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    program = main_program or framework.default_main_program()
    inference_program = prune_program(program, feeded_var_names,
                                     [v.name for v in target_vars])
    _ensure_dir(dirname)
    desc = _program_to_desc(inference_program)
    desc["_feed_names"] = list(feeded_var_names)
    desc["_fetch_names"] = [v.name for v in target_vars]
    with open(os.path.join(dirname, model_filename or "__model__"),
              "wb") as f:
        pickle.dump(desc, f, protocol=2)
    if not program_only:
        scope = global_scope()
        params = _collect(inference_program, is_persistable, scope)
        _save_dict(dirname, params, params_filename)
    return [v.name for v in target_vars]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    import jax.numpy as jnp

    with open(os.path.join(dirname, model_filename or "__model__"),
              "rb") as f:
        desc = pickle.load(f)
    program = _desc_to_program(desc)
    feed_names = desc.get("_feed_names", [])
    fetch_names = desc.get("_fetch_names", [])
    scope = global_scope()
    persist_names = [v.name for v in program.list_vars() if v.persistable]
    d = _load_dict(dirname, persist_names, params_filename)
    for k, v in d.items():
        scope.set_var(k, jnp.asarray(v))
    fetch_targets = [program.global_block().var(n) for n in fetch_names]
    return program, feed_names, fetch_targets


# -- program (de)serialization (reference: framework.proto round trip) ------

def _program_to_desc(program: Program) -> dict:
    blocks = []
    for b in program.blocks:
        vars_d = []
        for v in b.vars.values():
            vars_d.append({
                "name": v.name, "shape": list(v.shape), "dtype": v.dtype,
                "persistable": v.persistable,
                "stop_gradient": v.stop_gradient,
                "is_parameter": isinstance(v, Parameter),
                "trainable": getattr(v, "trainable", True),
                "is_data": v.is_data,
            })
        ops_d = [{"type": op.type, "inputs": op.input_names,
                  "outputs": op.output_names, "attrs": op.attrs}
                 for op in b.ops]
        blocks.append({"idx": b.idx, "parent_idx": b.parent_idx,
                       "vars": vars_d, "ops": ops_d})
    return {"blocks": blocks, "random_seed": program.random_seed,
            "version": 1}


def _desc_to_program(desc: dict) -> Program:
    p = Program()
    p.random_seed = desc.get("random_seed", 0)
    p.blocks = []
    for bd in desc["blocks"]:
        b = framework.Block(p, bd["idx"], bd["parent_idx"])
        for vd in bd["vars"]:
            if vd.get("is_parameter"):
                v = Parameter(b, shape=vd["shape"], dtype=vd["dtype"],
                              name=vd["name"],
                              trainable=vd.get("trainable", True))
            else:
                v = Variable(b, name=vd["name"], shape=vd["shape"],
                             dtype=vd["dtype"],
                             persistable=vd["persistable"],
                             stop_gradient=vd.get("stop_gradient", False),
                             is_data=vd.get("is_data", False))
            b.vars[v.name] = v
        for od in bd["ops"]:
            op = framework.Operator(b, od["type"])
            op.input_names = {k: list(v) for k, v in od["inputs"].items()}
            op.output_names = {k: list(v) for k, v in od["outputs"].items()}
            op.attrs = dict(od["attrs"])
            b.ops.append(op)
        p.blocks.append(b)
    p._version = 1
    return p


def prune_program(program: Program, feed_names, fetch_names) -> Program:
    """Prune to the subgraph reaching fetch from feed (reference:
    framework/prune.cc); also drops backward/optimizer ops."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block()
    needed = set(fetch_names)
    keep = []
    # sub-block-aware reads/writes: control-flow ops (while/cond/scan)
    # declare no outputs — their effect is writes inside the sub-block,
    # which output_arg_names alone would miss, silently pruning the
    # whole loop out of the inference program
    from .lowering import _op_reads_writes

    for op in reversed(block.ops):
        if op.type == "backward":
            continue
        reads, writes = _op_reads_writes(op)
        if set(writes) & needed:
            keep.append(op)
            needed |= set(reads)
    block.ops = list(reversed(keep))
    pruned._version += 1
    return pruned


def get_program_persistable_vars(program):
    return [v for v in program.list_vars() if v.persistable]


def get_parameter_value(para, executor=None):
    """Numpy value of a Parameter from the global scope (reference:
    io.py get_parameter_value)."""
    import numpy as np

    from ..core.scope import global_scope

    v = global_scope().find_var(para.name)
    if v is None:
        raise ValueError(
            "parameter %r is absent from the scope — run the startup "
            "program" % para.name)
    return np.asarray(v)


def get_parameter_value_by_name(name, executor=None, program=None):
    """reference: io.py get_parameter_value_by_name."""
    program = program or framework.default_main_program()
    var = program.global_block()._find_var_recursive(name)
    if var is None:
        raise ValueError("no parameter named %r in the program" % name)
    return get_parameter_value(var, executor)


def get_program_parameter(program):
    """All Parameter vars of a program (reference: io.py
    get_program_parameter)."""
    return list(program.all_parameters())


def is_belong_to_optimizer(var):
    """Optimizer-state detection: accumulators are named
    '<OptimizerClass>_<n>_<param>_<slot>_<n>' by
    Optimizer._add_accumulator, so the unambiguous marker is the
    'Optimizer_' class-name segment (plus the lr variable); user params
    named 'linear'/'accum' etc. are NOT flagged."""
    name = getattr(var, "name", "")
    return bool(getattr(var, "persistable", False)) and (
        "Optimizer_" in name or name.startswith("learning_rate"))


def load_program_state(model_path, var_list=None):
    """Load a `fluid.save` archive (params + optimizer state) into a
    {name: ndarray} dict without touching the scope (reference: io.py
    load_program_state)."""
    names = set(v.name for v in var_list) if var_list else None

    def filt(d):
        return (d if names is None
                else {k: v for k, v in d.items() if k in names})

    if os.path.isdir(model_path):
        return filt(_load_dict(model_path,
                               sorted(names) if names else None))
    d = os.path.dirname(model_path) or "."
    f = os.path.basename(model_path)
    state = {}
    found = False
    # merge both archives fluid.save writes, like load() does
    for suffix in (".pdparams", ".pdopt", ""):
        cand = f + suffix
        if suffix == "" and found:
            continue
        if os.path.exists(os.path.join(d, cand)) and                 os.path.isfile(os.path.join(d, cand)):
            state.update(filt(_load_dict(d, filename=cand)))
            found = True
    if not found:
        raise IOError("no saved program state at %r" % model_path)
    return state


def set_program_state(program, state_dict):
    """Bind a {name: ndarray} dict into the scope for the program's
    persistable vars (reference: io.py set_program_state)."""
    import jax.numpy as jnp

    from ..core.scope import global_scope

    unused = dict(state_dict)
    for var in program.list_vars():
        if not getattr(var, "persistable", False):
            continue
        if var.name in unused:
            global_scope().set_var(var.name,
                                   jnp.asarray(unused.pop(var.name)))
    return unused
