"""fluid.install_check (reference: python/paddle/fluid/install_check.py):
run_check() trains a tiny linear model in both execution modes and over
the local device mesh, printing a success message."""
from __future__ import annotations

import numpy as np


def run_check():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import framework

    # static
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[2], dtype="float32")
            y = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(y)
            fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    out = exe.run(main, feed={"x": np.ones((4, 2), "float32")},
                  fetch_list=[loss], scope=scope)
    assert np.isfinite(np.asarray(out[0])).all()

    # dygraph
    from paddle_tpu.fluid import dygraph

    with dygraph.guard():
        lin = dygraph.nn.Linear(2, 1)
        t = dygraph.to_variable(np.ones((4, 2), "float32"))
        l = fluid.layers.mean(lin(t))
        l.backward()
        assert lin.weight._grad is not None

    import jax

    print("Your paddle-tpu works well on %d %s device(s)."
          % (len(jax.devices()), jax.default_backend().upper()))
    print("install_check passed.")


if __name__ == "__main__":
    run_check()
