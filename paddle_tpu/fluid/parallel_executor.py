"""fluid.ParallelExecutor — legacy data-parallel executor, as a compat
class over CompiledProgram.with_data_parallel.

Reference parity: `python/paddle/fluid/parallel_executor.py:29`
(ParallelExecutor.__init__/run/drop_local_exe_scopes). TPU-native: the
reference's SSA-graph multi-device executor collapsed into XLA — the
class builds the same CompiledProgram DP path `Executor.run` serves
(shard_map over the device mesh), so the legacy idiom
``fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name)`` runs
unmodified. Its `run` keeps the legacy contract: fetch_list FIRST,
feed/feed_dict keywords, per-run fetch targets.
"""
from __future__ import annotations

from . import framework
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from .executor import Executor


class ParallelExecutor:
    def __init__(self, use_cuda, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from ..core.scope import global_scope

        self._places = (framework.cuda_places() if use_cuda
                        else framework.cpu_places())
        self._scope = scope if scope is not None else global_scope()
        main_program = (main_program if main_program is not None
                        else framework.default_main_program())
        self._build_strategy = build_strategy or BuildStrategy()
        if num_trainers != 1:
            self._build_strategy.num_trainers = num_trainers
            self._build_strategy.trainer_id = trainer_id
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        share = getattr(share_vars_from, "_compiled_program", None) \
            if share_vars_from is not None else None
        self._compiled_program = CompiledProgram(
            main_program, build_strategy=self._build_strategy
        ).with_data_parallel(
            loss_name=loss_name, exec_strategy=self._exec_strategy,
            share_vars_from=share)
        self._exe = Executor(self._places[0])

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        """Legacy argument order: fetch_list positionally first;
        feed_dict is the deprecated alias for feed."""
        if feed is None:
            feed = feed_dict
        return self._exe.run(self._compiled_program, feed=feed,
                             fetch_list=fetch_list,
                             scope=self._scope,
                             return_numpy=return_numpy)

    def drop_local_exe_scopes(self):
        """Reference drops the per-place local scopes between
        iterations; the XLA path holds no per-place scopes, so there is
        nothing to free — kept for API compatibility."""

    @property
    def device_count(self):
        return len(self._places)
