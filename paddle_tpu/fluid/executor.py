"""Executor: feed -> compiled block -> fetch.

Reference parity: `python/paddle/fluid/executor.py` (`Executor.run`
`executor.py:896`, `_run_impl:1087`) driving the C++ op-loop executor
(`framework/executor.cc:184-471`). TPU-native: `run` lowers the block to a
single jitted XLA computation (cached by program version + feed shapes;
reference analogue: the prepared-ctx program cache `executor.cc:184`),
device_puts the feeds, executes, and device_gets the fetches. Persistable
state lives in the Scope as device-resident jax Arrays between runs —
feed/fetch are the only host<->HBM transfers per step.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from . import framework, lowering
from ..core.scope import Scope, global_scope
from ..core.types import to_numpy_dtype


class Executor:
    def __init__(self, place=None):
        self.place = place if place is not None else \
            framework._current_expected_place()
        self._cache = {}

    # -- public API --------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            feed_var_name="feed", fetch_var_name="fetch",
            return_numpy=True, use_program_cache=True):
        program = program or framework.default_main_program()
        # CompiledProgram front (compiler.py) wraps a Program
        from . import compiler

        if isinstance(program, compiler.CompiledProgram):
            program = program._unwrap()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []

        fetch_names = [
            f.name if isinstance(f, framework.Variable) else str(f)
            for f in fetch_list]

        block = program.global_block()
        feed_arrays = self._prepare_feed(block, feed)

        key = self._cache_key(program, feed_arrays, fetch_names, scope)
        entry = self._cache.get(key) if use_program_cache else None
        if entry is None:
            state_in, _ = lowering.analyze_block(
                block, list(feed_arrays), fetch_names)
            state_specs = {}
            for n in state_in:
                v = scope.find_var(n)
                if v is not None:
                    state_specs[n] = v
            entry = lowering.compile_block(
                program, block, feed_arrays, fetch_names, state_specs)
            if use_program_cache:
                self._cache[key] = entry

        states_mut = {n: scope.find_var(n) for n in entry.state_mut_names}
        states_ro = {n: scope.find_var(n) for n in entry.state_ro_names}
        seed = framework._global_seed_and_bump(program)
        feeds_dev = self._shard_feeds(entry, feed_arrays)
        fetches, new_states = entry.jitted(feeds_dev, states_mut,
                                           states_ro,
                                           np.uint32(seed % (2**31)))
        for n, v in new_states.items():
            scope.set_var(n, v)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)

    # -- helpers -----------------------------------------------------------
    def _prepare_feed(self, block, feed) -> Dict[str, np.ndarray]:
        out = {}
        for name, value in feed.items():
            arr = np.asarray(value)
            v = block._find_var_recursive(name)
            if v is not None:
                want = to_numpy_dtype(v.dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
            out[name] = arr
        return out

    def _shard_feeds(self, entry, feed_arrays):
        import jax

        if entry.mesh is None:
            return {n: jax.numpy.asarray(a) for n, a in feed_arrays.items()}
        from jax.sharding import NamedSharding, PartitionSpec as P

        out = {}
        for n, a in feed_arrays.items():
            sh = NamedSharding(entry.mesh, P(entry.dp_axis))
            out[n] = jax.device_put(a, sh)
        return out

    def _cache_key(self, program, feed_arrays, fetch_names, scope):
        feed_key = tuple(sorted(
            (n, a.shape, str(a.dtype)) for n, a in feed_arrays.items()))
        return (id(program), program._version, feed_key, tuple(fetch_names),
                id(scope))

    def close(self):
        self._cache.clear()

    # dataset-training entry points (reference: executor.py:1454) are
    # provided by the trainer runtime in paddle_tpu.fluid.trainer
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        from .trainer import train_from_dataset as _tfd

        return _tfd(self, program, dataset, scope, fetch_list, print_period)

    def infer_from_dataset(self, *args, **kwargs):
        return self.train_from_dataset(*args, **kwargs)
