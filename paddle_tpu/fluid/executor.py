"""Executor: feed -> compiled block -> fetch.

Reference parity: `python/paddle/fluid/executor.py` (`Executor.run`
`executor.py:896`, `_run_impl:1087`) driving the C++ op-loop executor
(`framework/executor.cc:184-471`). TPU-native: `run` lowers the block to a
single jitted XLA computation (cached by program version + feed shapes;
reference analogue: the prepared-ctx program cache `executor.cc:184`),
device_puts the feeds, executes, and device_gets the fetches. Persistable
state lives in the Scope as device-resident jax Arrays between runs —
feed/fetch are the only host<->HBM transfers per step.
"""
from __future__ import annotations

import time as _time
from typing import Dict, List, Optional

import numpy as np

from . import framework, lowering
from ..core.scope import Scope, global_scope
from ..core.types import to_numpy_dtype
from ..reader.prefetcher import is_donatable, is_on_device, \
    mark_donatable


class LazyFetch:
    """Device-resident fetch handle (`Executor.run(...,
    return_numpy=False)`): the host does NOT block on the step that
    produced it. Materialize explicitly with `.numpy()` (or implicitly
    via `np.asarray` / `float`); `.value` is the raw device array;
    `.block_until_ready()` waits without copying. Every host
    materialization is accounted to the profiler's `sync` step phase,
    so deferred-fetch loops show exactly when they blocked."""

    __slots__ = ("_v",)

    def __init__(self, v):
        self._v = v

    @property
    def value(self):
        return self._v

    @property
    def shape(self):
        return tuple(self._v.shape)

    @property
    def dtype(self):
        return self._v.dtype

    def block_until_ready(self):
        import jax

        jax.block_until_ready(self._v)
        return self

    def numpy(self):
        from . import profiler as _prof

        t0 = _time.perf_counter()
        out = Executor._fetch_to_numpy(self._v)
        _prof.record_step_phase("sync", _time.perf_counter() - t0, t0)
        return out

    def __array__(self, dtype=None, copy=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self.numpy().reshape(-1)[0])

    def __repr__(self):
        return "LazyFetch(shape=%s, dtype=%s)" % (self.shape, self.dtype)


class Executor:
    def __init__(self, place=None):
        from collections import OrderedDict

        self.place = place if place is not None else \
            framework._current_expected_place()
        # LRU of compiled executables, bounded by
        # FLAGS_tpu_compile_cache_size (dead programs no longer pin
        # compiled artifacts forever)
        self._cache = OrderedDict()

    # -- public API --------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            feed_var_name="feed", fetch_var_name="fetch",
            return_numpy=True, use_program_cache=True):
        """One step. Per-step wall time is split into the profiler's
        step phases (feed / dispatch / sync / host, plus compile on a
        cache miss) so infeed/compute overlap is measurable — see
        fluid/profiler.py step_phase_summary."""
        from . import profiler as _prof
        from .. import observability as _obs

        # hang forensics: stamp "inside a step" on the armed watchdog
        # (FLAGS_tpu_hang_timeout_s); a bare global check when off
        _obs.on_step_begin()
        t_step = _time.perf_counter()
        ph = {"feed": 0.0, "dispatch": 0.0, "sync": 0.0, "compile": 0.0}
        comm0 = _prof.step_phase_total("comm")
        lanes0 = {ln: _prof.step_phase_total(ln)
                  for ln in ("comm_ici", "comm_dcn", "comm_mp")}
        try:
            return self._run_impl(program, feed, fetch_list, scope,
                                  return_numpy, use_program_cache, ph)
        finally:
            total = _time.perf_counter() - t_step
            if ph["dispatch"] > 0.0:
                # a run that failed before dispatching is not a step:
                # recording it would inflate the summary's per-step
                # denominator and skew every average
                for name in ("feed", "dispatch", "sync"):
                    _prof.record_step_phase(name, ph[name])
                if ph["compile"]:
                    _prof.record_step_phase("compile", ph["compile"])
                # host-collective time recorded DURING this step (PS
                # barriers, cross-rank agreement) already counted
                # itself into the comm phase — keep host disjoint
                comm_dt = _prof.step_phase_total("comm") - comm0
                host_dt = max(0.0, total - sum(ph.values()) - comm_dt)
                _prof.record_step_phase("host", host_dt)
                # one per-step telemetry record (observability registry:
                # JSONL sink + flight-recorder ring + capture poll);
                # a few dict ops when telemetry is idle
                from .. import observability as _obs

                rec = {
                    "feed_ms": ph["feed"] * 1e3,
                    "dispatch_ms": ph["dispatch"] * 1e3,
                    "comm_ms": comm_dt * 1e3,
                    "sync_ms": ph["sync"] * 1e3,
                    "host_ms": host_dt * 1e3,
                    "compile_ms": ph["compile"] * 1e3,
                    "total_ms": total * 1e3,
                }
                # multi-pod comm lanes: the slice of comm_ms spent on
                # cross-pod (dcn) vs intra-pod (ici) host coordination
                # — present only when a pod topology recorded any
                for ln, t0v in lanes0.items():
                    lane_dt = _prof.step_phase_total(ln) - t0v
                    if lane_dt > 0.0:
                        rec[ln + "_ms"] = lane_dt * 1e3
                # epoch-domain step START (t_step is perf_counter
                # time — unusable next to the event records' epoch
                # ts in the same JSONL stream)
                _obs.on_executor_step(rec, ts=_time.time() - total)

    def _run_impl(self, program, feed, fetch_list, scope, return_numpy,
                  use_program_cache, ph):
        from . import profiler as _prof

        def _mark(name, t0):
            # accumulate into this step's phase AND emit the live
            # chrome-trace span at its real start time
            d = _time.perf_counter() - t0
            ph[name] += d
            _prof.record_step_trace(name, t0, d)

        program = program or framework.default_main_program()
        # CompiledProgram front (compiler.py) wraps a Program
        from . import compiler

        _compiled = program if isinstance(
            program, compiler.CompiledProgram) else None
        if _compiled is not None:
            program = _compiled._unwrap()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []

        fetch_names = [
            f.name if isinstance(f, framework.Variable) else str(f)
            for f in fetch_list]

        if _compiled is not None:
            # BuildStrategy-driven fusion rewrites (first run decides:
            # idempotent markers make later runs no-ops). Fetch names
            # guard the passes from fusing away an observed var.
            bsty = _compiled._build_strategy
            if getattr(bsty, "fuse_all_optimizer_ops", None):
                from .fuse_optimizer import fuse_optimizer_ops

                fuse_optimizer_ops(program)
            if getattr(bsty, "fuse_elewise_add_act_ops", None):
                from .fusion_passes import fuse_elewise_add_act

                fuse_elewise_add_act(program, keep_names=fetch_names)
            if getattr(bsty, "fuse_bn_act_ops", None):
                from .fusion_passes import fuse_bn_act

                fuse_bn_act(program, keep_names=fetch_names)

        # the fusion passes run once, keyed to the FIRST run's fetch
        # list; a later run fetching a since-fused-away intermediate
        # must get an error naming the responsible knob, not lowering's
        # generic "never computed"
        fused_away = getattr(program, "_fused_away_vars", {})
        for n in fetch_names:
            if n in fused_away:
                raise RuntimeError(
                    "fetch var %r was removed from this program by the "
                    "BuildStrategy.%s fusion pass (applied on the "
                    "program's first run, which did not fetch it). "
                    "Fetch it on the first run, disable the knob, or "
                    "rebuild the program." % (n, fused_away[n]))

        # PS mode: the communicator needs this step's grads — extend the
        # fetch list internally (reference: send ops read the grad vars)
        ps_cfg = getattr(program, "_ps_cfg", None)
        n_user_fetches = len(fetch_names)
        if ps_cfg is not None and ps_cfg["mode"] in ("sync", "async", "half_async"):
            fetch_names = fetch_names + [
                g for g in sorted(ps_cfg["grad_of"])
                if g not in fetch_names]
            fetch_names = fetch_names + [
                m["grad"] for m in ps_cfg.get("sparse_tables",
                                              {}).values()
                if m["grad"] not in fetch_names]

        # elastic (strategy.elastic): auto-resume from the latest
        # checkpoint before the first step of this program
        ecfg = getattr(program, "_elastic_cfg", None)
        if ecfg is not None and not ecfg.get("_resumed"):
            self._elastic_resume(program, ecfg, scope)

        block = program.global_block()
        _t = _time.perf_counter()
        feed_arrays = self._prepare_feed(block, feed)
        _mark("feed", _t)
        if ps_cfg is not None and ps_cfg.get("sparse_tables"):
            # distributed_lookup_table: fetch this batch's unique rows
            # into the @PREFETCH/@REMAP feeds before compiling/running
            comm = self._ps_communicator(program, ps_cfg, scope)
            comm.prefetch(feed_arrays, scope)

        key = self._cache_key(program, feed_arrays, fetch_names, scope)
        entry = self._cache.get(key) if use_program_cache else None
        if entry is not None:
            self._cache.move_to_end(key)
        tail_n = None
        fresh_compile = False
        if entry is None and use_program_cache:
            # batch-tail bucketing (SURVEY §7 hard part (d); reference
            # contract executor.cc:184 — any batch size runs without
            # recompiling): if a cached bucket's batch is an integer
            # multiple of this batch, replicate rows m times and run the
            # CACHED executable. Row replication is exact for mean-type
            # losses, their grads, and biased batch statistics (each row
            # appears exactly m times), so the step matches the
            # unbucketed one bit-for-bit up to fp reduction order; RNG
            # ops sample per padded row (documented divergence).
            # Non-divisible tails fall through to a one-time compile
            # that the cache then amortizes across epochs.
            hit = self._find_tail_bucket(program, feed_arrays,
                                         fetch_names, scope)
            if hit is not None:
                bkey, m, tail_n, rep_names = hit
                entry = self._cache[bkey]
                self._cache.move_to_end(bkey)
                feed_arrays = {
                    n: (self._replicate_rows(a, m)
                        if n in rep_names else a)
                    for n, a in feed_arrays.items()}
        if entry is None:
            _t = _time.perf_counter()
            entry = self._compile_and_cache(program, block, feed_arrays,
                                            fetch_names, scope, key,
                                            use_program_cache)
            fresh_compile = True
            _mark("compile", _t)

        states_mut = {n: scope.find_var(n) for n in entry.state_mut_names}
        states_ro = {n: scope.find_var(n) for n in entry.state_ro_names}
        if entry.sharded_state:
            # ZeRO-1 layout: sharded optimizer state lives in the scope
            # as flat (padded,) buffers NamedSharding'd over the dp axis
            # — convert once (startup-initialized / checkpoint-restored
            # values arrive at their logical shapes)
            from ..parallel import sharded_update as _su

            for n, info in entry.sharded_state.items():
                v = states_mut.get(n)
                # model-sharded ZeRO vars: the device layout is the
                # model-major concat of mp per-member padded flats
                expect = (info.padded * info.mp,) \
                    if info.tp_dim is not None else (info.padded,)
                if v is not None and \
                        tuple(getattr(v, "shape", ())) != expect:
                    v = _su.to_sharded_global(v, info, entry.mesh,
                                              entry.dp_axis)
                    states_mut[n] = v
                    scope.set_var(n, v)
        if entry.sparse_tables:
            # vocab-sharded embedding layout: tables + per-row moments
            # live in the scope as (padded_rows, dim) buffers
            # NamedSharding'd P(axis) on the vocab axis — convert once
            # (logical-shape values from startup/checkpoint restore, or
            # a stale world's padding after an elastic N' restart)
            from ..embedding import engine as _emb

            for n, info in entry.sparse_tables.items():
                for d in (states_mut, states_ro):
                    v = d.get(n)
                    if v is not None and tuple(getattr(v, "shape", ())) \
                            != info.device_shape:
                        v = _emb.to_row_sharded_global(
                            v, info, entry.mesh, entry.dp_axis)
                        d[n] = v
                        scope.set_var(n, v)
        self._check_sparse_ids(program, feed_arrays)
        if fresh_compile:
            # OOM pre-flight (FLAGS_tpu_hbm_budget_mb, off by default):
            # reject a program whose modeled HBM peak exceeds the
            # budget BEFORE the first dispatch, naming the consumers.
            # A failed gate EVICTS the just-cached entry — same
            # invariant as the post-compile static checks: a caught-
            # and-retried run must re-enter the gate, not cache-hit
            # past it and dispatch the known-over-budget program
            try:
                self._hbm_preflight(program, entry, feed_arrays,
                                    states_mut, states_ro, scope)
            except Exception:
                self._cache.pop(key, None)
                raise
        if fresh_compile:
            # persistent compile-cache tier
            # (FLAGS_tpu_compile_cache_dir): fingerprint the lowered
            # StableHLO at the exact avals the dispatch below will use
            # and look up the cross-process index — the lowering also
            # warms jax's trace cache, so the first dispatch re-pays
            # (at most) the backend compile the persistent tier
            # eliminates. No-op when the tier is off.
            _t = _time.perf_counter()
            self._cc_classify(entry, feed_arrays, states_mut, states_ro)
            _mark("compile", _t)
        seed = framework._global_seed_and_bump(program)
        _t = _time.perf_counter()
        feeds_dev = self._shard_feeds(entry, feed_arrays)
        _mark("feed", _t)
        cc_snap = None
        if fresh_compile and entry.cc_fingerprint is not None:
            from . import compile_cache as _cc

            cc_snap = (_cc.jax_stats(), _time.time())
        _t = _time.perf_counter()
        try:
            fetches, new_states = entry.jitted(feeds_dev, states_mut,
                                               states_ro,
                                               np.uint32(seed % (2**31)))
        except Exception as e:
            from ..observability import attribution as _attr

            if _attr.is_resource_exhausted(e):
                # OOM forensics: land the attributed memory breakdown
                # in the flight-recorder dump so the postmortem answers
                # "what was resident" without a repro; the original
                # error still propagates
                _attr.record_oom_forensics(
                    program, block, self._shard_plan_of(program),
                    self._shard_count(entry), feed_arrays,
                    list(entry.state_mut_names)
                    + list(entry.state_ro_names), scope, e)
            raise
        _mark("dispatch", _t)
        if cc_snap is not None:
            # hit/miss verdict + compile_cache event; the measured
            # backend-compile seconds move from the dispatch phase into
            # compile_ms, so a warm restart's first step shows
            # compile_ms ~ 0 where a cold one shows the full XLA cost
            self._cc_finish(entry, ph, cc_snap)
        if fresh_compile:
            self._maybe_elastic_warmup(program, entry, feed_arrays,
                                       fetch_names, scope)
        for n, v in new_states.items():
            scope.set_var(n, v)
        if ecfg is not None:
            self._elastic_tick(program, ecfg, scope)
        if tail_n is not None:
            # un-replicate batch-majored fetches (leading program dim -1
            # marks the batch axis; fixed-shape fetches pass through)
            sliced = []
            for fname, v in zip(fetch_names, fetches):
                fv = block._find_var_recursive(fname)
                shp = tuple(getattr(fv, "shape", ()) or ()) if fv is not None \
                    else ()
                if shp[:1] == (-1,) and getattr(v, "ndim", 0) >= 1:
                    v = v[:tail_n]
                sliced.append(v)
            fetches = sliced

        from ..utils.flags import get_flag

        if get_flag("FLAGS_check_nan_inf"):
            _t = _time.perf_counter()
            self._check_nan_inf(fetch_names, fetches, new_states)
            _mark("sync", _t)
        if get_flag("FLAGS_benchmark"):
            # per-step device sync (reference: operator.cc:997)
            import jax

            _t = _time.perf_counter()
            jax.block_until_ready(fetches)
            _mark("sync", _t)

        if ps_cfg is not None:
            comm = self._ps_communicator(program, ps_cfg, scope)
            if ps_cfg["mode"] in ("sync", "async", "half_async"):
                # the communicator pushes THIS step's grads over RPC —
                # a required host sync, kept on every step
                _t = _time.perf_counter()
                sparse_gvals = {
                    w: np.asarray(fetches[fetch_names.index(m["grad"])])
                    for w, m in ps_cfg.get("sparse_tables", {}).items()}
                gvals = {}
                for g, p in ps_cfg["grad_of"].items():
                    gvals[p] = np.asarray(fetches[fetch_names.index(g)])
                _mark("sync", _t)
                if sparse_gvals:
                    comm.push_sparse(sparse_gvals)
                comm.step(gvals, scope)
            else:
                comm.step({}, scope)
            fetches = fetches[:n_user_fetches]
        if return_numpy:
            _t = _time.perf_counter()
            out = [self._fetch_to_numpy(v) for v in fetches]
            _mark("sync", _t)
            return out
        return [LazyFetch(v) for v in fetches]

    @staticmethod
    def _check_sparse_ids(program, feed_arrays):
        """Host-side OOV pre-check for vocab-sharded embedding feeds:
        an out-of-range id raises (FLAGS_tpu_static_checks=error) or
        warns (=warn) with the table/feed named BEFORE the dispatch —
        the same fatal/non-fatal split as every other checker behind
        the flag — instead of the dense path's silent clipped gather.
        O(batch) numpy per step, only for programs that actually
        carry a sparse plan."""
        plan = getattr(program, "_sparse_plan", None)
        if plan is None:
            return
        from ..utils.flags import get_flag

        mode = str(get_flag("FLAGS_tpu_static_checks", "off")
                   or "off").lower()
        if mode not in ("warn", "error"):
            return
        from ..embedding import engine as _emb

        try:
            _emb.check_oov_feeds(plan, feed_arrays)
        except ValueError as e:
            if mode == "error":
                raise
            import warnings

            warnings.warn("tpu-lint: " + str(e))

    #: checkers that need nothing from compile_block (no shard plan),
    #: run before the XLA compile so error mode fails fast
    _PRE_COMPILE_CHECKERS = ("collective-divergence", "donation-safety",
                             "host-sync", "dtype-contract")

    @staticmethod
    def _static_checks(program, feed_arrays, fetch_names, checkers=None):
        """Opt-in compile-time tpu-lint (paddle_tpu/analysis):
        FLAGS_tpu_static_checks="warn" surfaces every finding as a
        python warning; "error" raises on error-severity findings
        (collective divergence, read-after-donate, fetch-in-loop,
        shard-plan violations) BEFORE the first dispatch — the IR-only
        checkers even before the XLA compile. Runs only on
        compile-cache misses — steady-state steps never pay."""
        from ..utils.flags import get_flag

        mode = str(get_flag("FLAGS_tpu_static_checks", "off")
                   or "off").lower()
        if mode not in ("warn", "error"):
            return
        from .. import analysis

        findings = analysis.run_static_checks(
            program, feed_names=list(feed_arrays),
            fetch_names=list(fetch_names), checkers=checkers)
        if not findings:
            return
        import warnings

        for f in findings:
            warnings.warn("tpu-lint: " + analysis.format_finding(f))
        errors = [f for f in findings if f.severity == "error"]
        if mode == "error" and errors:
            raise RuntimeError(
                "FLAGS_tpu_static_checks=error: %d static-check "
                "error(s) in this program:\n%s" % (
                    len(errors), "\n".join(
                        "  " + analysis.format_finding(f)
                        for f in errors)))

    def _compile_and_cache(self, program, block, feed_arrays,
                           fetch_names, scope, key, use_program_cache):
        """The fresh-compile path shared by run() and warmup():
        pre-compile static checks -> compile_block -> post-compile
        checks -> LRU insert. Evicted entries drop their AOT-compiled
        artifacts EAGERLY (a dead in-memory entry must not pin
        compiled XLA executables in host RAM); the persistent tier
        (FLAGS_tpu_compile_cache_dir) survives eviction, so a
        re-admitted program is a persistent-cache hit, not a fresh
        compile."""
        from . import compile_cache as _cc

        _cc.ensure()
        # tpu-lint, pre-compile leg (FLAGS_tpu_static_checks): the
        # IR-only checkers need nothing from XLA, so in error mode
        # a known-bad program is rejected BEFORE paying the
        # (potentially tens of seconds) compile below
        self._static_checks(program, feed_arrays, fetch_names,
                            checkers=self._PRE_COMPILE_CHECKERS)
        state_in, _ = lowering.analyze_block(
            block, list(feed_arrays), fetch_names)
        state_specs = {}
        for n in state_in:
            v = scope.find_var(n)
            if v is not None:
                state_specs[n] = v
        entry = lowering.compile_block(
            program, block, feed_arrays, fetch_names, state_specs)
        from ..utils.flags import get_flag

        if get_flag("FLAGS_enable_unused_var_check"):
            # reference: framework/unused_var_check.cc (op inputs
            # declared but never read); block-level equivalent here
            import warnings

            used = set()
            for op in block.ops:
                used.update(lowering._op_reads_writes(op)[0])
            unused = [n for n in feed_arrays if n not in used]
            if unused:
                warnings.warn(
                    "feed variables never read by the program: %s"
                    % unused)
        # tpu-lint, post-compile leg: zero1-invariants and
        # zero2-lifetimes verify the ShardedUpdatePlan that
        # compile_block just attached (program._shard_plan), so
        # they cannot run in the fail-fast leg above. MUST run
        # before the entry is cached: in error mode a caught-and-
        # retried run would otherwise cache-hit past the check and
        # dispatch the known-bad program
        self._static_checks(program, feed_arrays, fetch_names,
                            checkers=("zero1-invariants",
                                      "zero2-lifetimes",
                                      "sparse-update"))
        if use_program_cache:
            self._cache[key] = entry
            limit = int(get_flag("FLAGS_tpu_compile_cache_size", 128)
                        or 128)
            while len(self._cache) > limit:
                _, evicted = self._cache.popitem(last=False)
                evicted.aot_compiled = None
        return entry

    # -- persistent compile cache (fluid/compile_cache) -----------------
    def _cc_classify(self, entry, feed_arrays, states_mut, states_ro):
        """Persistent-tier classification of a fresh compile: lower
        the entry at the avals the dispatch will use, fingerprint the
        canonicalized StableHLO + mesh topology + lowering-relevant
        flags + jax version, and look up the cross-process index.
        Leaves cc_fingerprint None (classification off) when the tier
        is disabled or the entry is not jit-lowered."""
        from . import compile_cache as _cc

        if not _cc.enabled() or not hasattr(entry.jitted, "lower"):
            return
        try:
            favals = {n: self._aval_of(a)
                      for n, a in feed_arrays.items()}
            smut = {n: self._aval_of(v)
                    for n, v in states_mut.items()}
            sro = {n: self._aval_of(v)
                   for n, v in states_ro.items()}
            lowered = self._lower_entry(entry, favals, smut, sro)
            fp = _cc.fingerprint(lowered.as_text(), entry.mesh)
            entry.cc_fingerprint = fp
            entry.cc_prev = _cc.index_lookup(fp)
        except Exception:  # noqa: BLE001 - classification is telemetry
            entry.cc_fingerprint = None

    def _cc_finish(self, entry, ph, cc_snap, source="step"):
        """Close out a classified fresh compile after its first
        dispatch: re-attribute the measured backend-compile seconds
        from the dispatch phase into compile_ms, decide hit/miss, emit
        the `compile_cache` telemetry event, and write the index
        sentinel the next process's classification reads."""
        from . import compile_cache as _cc

        before, t0 = cc_snap
        d = _cc.stats_delta(before)
        comp_s = max(0.0, d["backend_compile_s"])
        if ph is not None and comp_s > 0.0 and ph["dispatch"] > 0.0:
            moved = min(comp_s, ph["dispatch"])
            # keep dispatch strictly positive: a zeroed dispatch would
            # drop the whole step from the phase summary
            ph["dispatch"] = max(ph["dispatch"] - moved, 1e-9)
            ph["compile"] += moved
        prev = entry.cc_prev
        hit = prev is not None or d["persistent_hits"] > 0
        saved_ms = max(0.0, d["saved_s"] * 1e3)
        nbytes = 0
        if prev is not None:
            saved_ms = max(saved_ms,
                           float(prev.get("compile_ms", 0.0))
                           - comp_s * 1e3)
            nbytes = int(prev.get("bytes", 0))
        elif not hit:
            nbytes = _cc.new_entry_bytes(t0)
        _cc.record_event("hit" if hit else "miss",
                         entry.cc_fingerprint,
                         compile_ms=comp_s * 1e3, saved_ms=saved_ms,
                         nbytes=nbytes, source=source)
        if prev is None and entry.cc_fingerprint:
            _cc.index_store(entry.cc_fingerprint,
                            {"compile_ms": round(comp_s * 1e3, 3),
                             "bytes": nbytes,
                             "mesh": _cc.mesh_signature(entry.mesh)})

    # -- AOT warmup (pre-compile before traffic / before failure) --------
    def warmup(self, program=None, shapes=None, meshes=None,
               fetch_list=None, scope=None, background=False):
        """Pre-compile this program BEFORE traffic or a failure pays
        the cost (ROADMAP direction 4; see paddle_tpu/parallel/README
        "Compilation cache & warmup"). For every feed-shape bucket in
        `shapes` (a list of dicts: feed name -> concrete shape tuple,
        example array, or jax.ShapeDtypeStruct) the program is
        compiled and ONE discarded step executes on state COPIES — so
        both jax's in-process executable cache and the persistent tier
        (FLAGS_tpu_compile_cache_dir) are warm, and the first real
        step of that shape dispatches with compile_ms ~ 0 — without
        mutating any scope state or the program's RNG stream.

        `meshes` additionally pre-populates the persistent tier for
        OTHER mesh topologies: "elastic" enumerates the likely N'
        shrink variants (parallel.env.elastic_mesh_variants), or pass
        explicit Mesh objects / device counts. Variant compiles run
        against a CLONE of the program and never touch the live
        program or the in-memory entry cache.

        background=True runs the whole warmup in a daemon thread (the
        elastic-variant recipe: schedule after the first step) and
        returns the Thread; its `.warmup_report` lands on completion.
        Foreground calls return the report dict: {"compiled": [...],
        "cached": [...], "skipped": [...]}."""
        from . import compiler

        program = program or framework.default_main_program()
        if isinstance(program, compiler.CompiledProgram):
            program = program._unwrap()
        scope = scope or global_scope()
        fetch_names = [
            f.name if isinstance(f, framework.Variable) else str(f)
            for f in (fetch_list or [])]
        if background:
            import threading

            def _bg():
                t.warmup_report = self._warmup_impl(
                    program, shapes, meshes, fetch_names, scope,
                    in_background=True)

            t = threading.Thread(target=_bg, daemon=True,
                                 name="paddle-tpu-warmup")
            t.warmup_report = None
            t.start()
            return t
        return self._warmup_impl(program, shapes, meshes, fetch_names,
                                 scope)

    def _warmup_impl(self, program, shapes, meshes, fetch_names, scope,
                     in_background=False, skip_base=False):
        from . import compile_cache as _cc

        _cc.ensure()
        report = {"compiled": [], "cached": [], "skipped": []}
        buckets = []
        for s in (shapes or []):
            try:
                buckets.append(self._warmup_feed_arrays(
                    program.global_block(), s))
            except Exception as e:  # noqa: BLE001 - best-effort API
                report["skipped"].append(
                    {"shapes": {k: repr(v) for k, v in s.items()},
                     "error": "%s: %s" % (type(e).__name__, e)})
        if not skip_base:
            for feed_arrays in buckets:
                # background warmup must not mutate the in-memory LRU
                # under the stepping main thread — persistent-tier
                # population only there
                self._warmup_one(program, feed_arrays, fetch_names,
                                 scope, report,
                                 use_cache=not in_background)
        if meshes is None:
            return report
        if not buckets:
            # no explicit shapes: reuse the feed buckets of this
            # program's already-compiled in-memory entries (the shapes
            # real traffic ran), so the runbook's post-first-step
            # `exe.warmup(meshes="elastic")` pre-populates the N'
            # variants without restating the batch geometry
            buckets = self._buckets_from_cache(program)
        if not buckets:
            report["skipped"].append(
                {"reason": "mesh variants need `shapes` (or a prior "
                           "run of this program to borrow them from)"})
            return report
        for ndev, mesh in self._warmup_meshes(program, meshes):
            if mesh is None:
                report["skipped"].append(
                    {"mesh_devices": ndev,
                     "reason": "exceeds the local device count"})
                continue
            clone = self._mesh_variant_program(program, mesh)
            if clone is None:
                report["skipped"].append(
                    {"mesh": _cc.mesh_signature(mesh),
                     "reason": "program not cloneable"})
                continue
            total = int(np.prod([mesh.shape[a]
                                 for a in mesh.axis_names]))
            for feed_arrays in buckets:
                bad = [n for n, a in feed_arrays.items()
                       if getattr(a, "ndim", 0) >= 1
                       and a.shape[0] % total]
                if bad:
                    report["skipped"].append({
                        "mesh_devices": ndev, "feeds": sorted(bad),
                        "reason": "batch not divisible by %d devices"
                                  % total})
                    continue
                self._warmup_one(clone, feed_arrays, fetch_names,
                                 scope, report, use_cache=False,
                                 variant=ndev)
        return report

    def _warmup_one(self, program, feed_arrays, fetch_names, scope,
                    report, use_cache=True, variant=None):
        import jax

        from . import compile_cache as _cc

        desc = {"feed_shapes": {n: tuple(a.shape)
                                for n, a in sorted(
                                    feed_arrays.items())}}
        if variant is not None:
            desc["mesh_devices"] = variant
        try:
            key = self._cache_key(program, feed_arrays, fetch_names,
                                  scope)
            if use_cache:
                entry = self._cache.get(key)
                if entry is not None:
                    self._cache.move_to_end(key)
                    report["cached"].append(desc)
                    return entry
            t0 = _time.perf_counter()
            entry = self._compile_and_cache(
                program, program.global_block(), feed_arrays,
                fetch_names, scope, key, use_cache)
            if not hasattr(entry.jitted, "lower"):
                desc["reason"] = "not jit-compiled (host/dynamic ops)"
                report["skipped"].append(desc)
                return entry
            # one DISCARDED step on state copies: lands the executable
            # in jax's in-process cache AND the persistent tier without
            # touching scope state or the program's RNG stream (the
            # jitted step donates its state args — hence the copies)
            # variant meshes get HOST copies: live state committed to
            # the full mesh cannot feed a jit over a different device
            # set ("incompatible devices"), while host arrays place
            # implicitly onto whatever mesh the variant uses
            host = variant is not None
            states_mut = {n: self._copy_state(scope.find_var(n),
                                              host=host)
                          for n in entry.state_mut_names}
            states_ro = ({n: self._copy_state(scope.find_var(n),
                                              host=True)
                          for n in entry.state_ro_names}
                         if host else
                         {n: scope.find_var(n)
                          for n in entry.state_ro_names})
            if entry.sharded_state:
                from ..parallel import sharded_update as _su

                for n, info in entry.sharded_state.items():
                    v = states_mut.get(n)
                    expect = (info.padded * info.mp,) \
                        if info.tp_dim is not None else (info.padded,)
                    if v is not None and tuple(
                            getattr(v, "shape", ())) != expect:
                        states_mut[n] = _su.to_sharded_global(
                            v, info, entry.mesh, entry.dp_axis)
            if entry.sparse_tables:
                from ..embedding import engine as _emb

                for n, info in entry.sparse_tables.items():
                    for d in (states_mut, states_ro):
                        v = d.get(n)
                        if v is not None and tuple(
                                getattr(v, "shape", ())) \
                                != info.device_shape:
                            d[n] = _emb.to_row_sharded_global(
                                v, info, entry.mesh, entry.dp_axis)
            # same gate invariant as run(): a warmup-cached entry must
            # not let the first real run cache-hit past the HBM
            # pre-flight (FLAGS_tpu_hbm_budget_mb; no-op when unset) —
            # an over-budget bucket is evicted and reported skipped
            try:
                self._hbm_preflight(program, entry, feed_arrays,
                                    states_mut, states_ro, scope)
            except Exception:
                if use_cache:
                    self._cache.pop(key, None)
                raise
            self._cc_classify(entry, feed_arrays, states_mut,
                              states_ro)
            cc_snap = (_cc.jax_stats(), _time.time())
            feeds_dev = self._shard_feeds(entry, feed_arrays)
            out = entry.jitted(feeds_dev, states_mut, states_ro,
                               np.uint32(0))
            jax.block_until_ready(out)
            del out, states_mut
            if entry.cc_fingerprint is not None:
                self._cc_finish(entry, None, cc_snap, source="warmup")
            desc["warmup_ms"] = round(
                (_time.perf_counter() - t0) * 1e3, 3)
            report["compiled"].append(desc)
            return entry
        except Exception as e:  # noqa: BLE001 - warmup is best-effort
            desc["error"] = "%s: %s" % (type(e).__name__, e)
            report["skipped"].append(desc)
            return None

    def _warmup_feed_arrays(self, block, spec):
        """A zero-filled feed dict from one warmup bucket spec: values
        are concrete shape tuples (dtype from the program var),
        example arrays, or ShapeDtypeStructs."""
        out = {}
        for name, v in spec.items():
            if hasattr(v, "shape") and hasattr(v, "dtype"):
                out[name] = np.zeros(tuple(v.shape), np.dtype(v.dtype))
                continue
            shape = tuple(int(d) for d in v)
            if any(d < 0 for d in shape):
                raise ValueError(
                    "warmup shapes must be concrete (got %r for %r) — "
                    "pass the real bucket batch, not -1"
                    % (shape, name))
            var = block._find_var_recursive(name)
            dtype = np.dtype(to_numpy_dtype(var.dtype)) \
                if var is not None else np.dtype("float32")
            out[name] = np.zeros(shape, dtype)
        return out

    def _buckets_from_cache(self, program):
        """Zero-filled feed dicts rebuilt from this program's cached
        in-memory entries' feed keys — the shapes real traffic already
        ran (mesh-variant warmup borrows them when the caller passes
        no explicit `shapes`)."""
        buckets = []
        seen = set()
        for k in self._cache:
            if k[0] != program._uid or k[2] in seen:
                continue
            seen.add(k[2])
            buckets.append({n: np.zeros(tuple(shape), np.dtype(dt))
                            for n, shape, dt in k[2]})
        return buckets

    @staticmethod
    def _warmup_meshes(program, meshes):
        """[(ndev, Mesh)] to pre-populate: "elastic" enumerates likely
        shrink variants from the program's current mesh; explicit Mesh
        objects and integer device counts pass through. An integer
        exceeding the local device count yields (n, None) so the
        caller reports it skipped instead of silently dropping it."""
        from ..parallel import env as penv

        if isinstance(meshes, str):
            if meshes != "elastic":
                raise ValueError("meshes: Mesh list, int list, or "
                                 "'elastic' (got %r)" % (meshes,))
            return penv.elastic_mesh_variants(
                getattr(program, "_mesh", None))
        out = []
        for m in meshes:
            if isinstance(m, int):
                out.append((m, penv.mesh_for_world(
                    m, dp_axis=getattr(program, "_dp_axis", "dp"))))
            else:
                out.append((int(np.prod([m.shape[a]
                                         for a in m.axis_names])), m))
        return out

    @staticmethod
    def _mesh_variant_program(program, mesh):
        """A clone of `program` pinned to `mesh`, for persistent-tier
        pre-population of another topology: the clone has its own _uid
        (separate in-memory key space) and grows its own shard plan;
        the live program's mesh/plan are never touched."""
        try:
            # clone() carries _data_parallel / _dp_axis / AMP marks;
            # only the mesh is overridden
            clone = program.clone()
        except Exception:  # noqa: BLE001 - exotic program front
            return None
        clone._mesh = mesh
        return clone

    @staticmethod
    def _copy_state(v, host=False):
        if v is None:
            return None
        if is_on_device(v):
            if host:
                return np.asarray(Executor._fetch_to_numpy(v))
            import jax.numpy as jnp

            return jnp.array(v, copy=True)
        return np.array(v, copy=True)

    def _maybe_elastic_warmup(self, program, entry, feed_arrays,
                              fetch_names, scope):
        """FLAGS_tpu_warmup_elastic_variants > 0: after the FIRST step
        of a data-parallel program, pre-compile the likely elastic N'
        mesh variants in a background daemon thread, so a future
        shrink's executables are already in the persistent tier before
        any rank dies. At most once per program."""
        from ..utils.flags import get_flag

        from . import compile_cache as _cc

        try:
            limit = int(get_flag("FLAGS_tpu_warmup_elastic_variants", 0)
                        or 0)
        except (TypeError, ValueError):
            limit = 0
        if limit <= 0 or not _cc.enabled() or entry.mesh is None \
                or not getattr(program, "_data_parallel", False) \
                or not hasattr(entry.jitted, "lower"):
            return
        started = getattr(self, "_elastic_warmed", None)
        if started is None:
            started = self._elastic_warmed = set()
        if program._uid in started:
            return
        started.add(program._uid)
        from ..parallel import env as penv

        import jax

        variants = penv.elastic_mesh_variants(entry.mesh, limit=limit)
        if not variants:
            return
        shapes = [{n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                   for n, a in feed_arrays.items()}]
        import threading

        def _bg():
            t.warmup_report = self._warmup_impl(
                program, shapes, [m for _, m in variants],
                list(fetch_names), scope, in_background=True,
                skip_base=True)

        t = threading.Thread(target=_bg, daemon=True,
                             name="paddle-tpu-elastic-warmup")
        t.warmup_report = None
        t.start()
        self._elastic_warmup_thread = t

    def live_resize(self, program, mesh=None, ndev=None, scope=None):
        """In-place device-tier mesh resize — the survivor half of the
        zero-downtime elasticity seam (distributed/preemption.py): no
        process exit, no checkpoint round-trip.

        Rewrites every sharded state var of `program` back to its
        logical host shape (parallel.sharded_update.
        reshard_scope_to_logical: ZeRO-1 moments, ZeRO-2 masters,
        row-sharded embedding tables), materializes every OTHER
        device-resident scope var to host numpy (a jax array committed
        to the old mesh's devices would fail the new mesh's dispatch
        with incompatible-devices — replicated params included), evicts
        the program's in-memory cache entries, and swaps
        ``program._mesh`` to the new topology. The next run() re-plans
        and re-shards exactly like an elastic cold restart restoring
        from a checkpoint — same `to_sharded_global` stale-padding trim,
        same pre-warmed N' executables (warmup(meshes="elastic") /
        FLAGS_tpu_warmup_elastic_variants) — so post-seam losses are
        bit-identical to that restart.

        Pass the target as a `mesh` or a device count `ndev`
        (parallel.env.mesh_for_world builds the hybrid or flat mesh).
        Publishes `live_resize` + `elastic_transition(mode=live)`
        events; returns the seam report dict."""
        import time as _time

        import jax

        from . import compiler
        from ..core.scope import global_scope
        from ..parallel import env as penv
        from ..parallel import sharded_update as _su

        t0 = _time.perf_counter()
        if isinstance(program, compiler.CompiledProgram):
            program = program._unwrap()
        scope = scope or global_scope()
        old_mesh = getattr(program, "_mesh", None)
        old_ndev = (int(np.prod(list(old_mesh.shape.values())))
                    if old_mesh is not None else 1)
        if mesh is None:
            if ndev is None:
                raise ValueError("live_resize needs mesh= or ndev=")
            mesh = penv.mesh_for_world(
                int(ndev), dp_axis=getattr(program, "_dp_axis", "dp"))
            if mesh is None:
                raise ValueError(
                    "no mesh for ndev=%d (local devices: %d)"
                    % (int(ndev), len(jax.devices())))
        new_ndev = int(np.prod(list(mesh.shape.values())))
        # 1) sharded state -> logical host numpy (moments, masters,
        #    embedding tables drop the old world's padded layout)
        n_state = _su.reshard_scope_to_logical(program, scope)
        # 2) every remaining device-resident scope var -> host numpy:
        #    committed-to-old-devices arrays (replicated params, BN
        #    stats) must not reach the new mesh's dispatch
        n_moved = 0
        for name in scope.local_var_names():
            v = scope.find_var(name)
            if v is not None and is_on_device(v):
                scope.set_var(name, np.asarray(self._fetch_to_numpy(v)))
                n_moved += 1
        # 3) drop the old topology's in-memory executables (the
        #    persistent tier keeps the new world's warmed variants)
        n_evicted = 0
        for k in [k for k in self._cache if k[0] == program._uid]:
            self._cache.pop(k, None)
            n_evicted += 1
        # 4) swap the mesh; next run() re-plans against it
        program._mesh = mesh
        report = {
            "old_world": old_ndev, "new_world": new_ndev,
            "n_state": n_state, "n_host_moved": n_moved,
            "n_evicted": n_evicted,
            "coordination_s": round(_time.perf_counter() - t0, 6),
        }
        try:
            from ..observability.registry import registry

            reg = registry()
            reg.event("live_resize", old_world=old_ndev,
                      new_world=new_ndev, mode="live", status="ok",
                      coordination_s=report["coordination_s"],
                      rebuild_s=report["coordination_s"])
            reg.event("elastic_transition", old_world=old_ndev,
                      new_world=new_ndev, mode="live",
                      coordination_s=report["coordination_s"])
        except Exception:  # noqa: BLE001 - telemetry only
            pass
        return report

    @staticmethod
    def _fetch_to_numpy(v):
        """Multi-host: a fetch sharded over remote processes is not fully
        addressable; return the locally-addressable shards concatenated
        (reference analogue: each trainer fetches its own scope)."""
        try:
            return np.asarray(v)
        except Exception:
            shards = getattr(v, "addressable_shards", None)
            if not shards:
                raise
            datas = [np.asarray(s.data) for s in shards]
            return np.concatenate(datas, axis=0) if len(datas) > 1 \
                else datas[0]

    def _ps_communicator(self, program, ps_cfg, scope=None):
        if not hasattr(self, "_ps_comms"):
            self._ps_comms = {}
        key = program._uid
        # a user-started fluid.communicator.Communicator wins — even
        # over a previously cached instance, so start()/stop()/start()
        # cycles actually swap the communicator the steps use
        user_comm = getattr(program, "_ps_comm", None)
        cached = self._ps_comms.get(key)
        if user_comm is not None and cached is not None \
                and cached is not user_comm \
                and not getattr(cached, "_completed", False):
            # don't abandon the replaced instance mid-flight: its
            # half-async sender thread would keep pushing stale grads
            cached.complete()
        comm = user_comm or cached
        if comm is not None and getattr(comm, "_completed", False):
            # stop()'d/closed communicators are dead — never step them
            comm = None
        if comm is None:
            from ..distributed.ps import PSCommunicator

            comm = PSCommunicator(ps_cfg)
        if scope is not None and \
                not getattr(comm, "_params_inited", False):
            comm.init_params(scope)
            comm._params_inited = True
        self._ps_comms[key] = comm
        return comm

    def _check_nan_inf(self, fetch_names, fetches, new_states):
        """FLAGS_check_nan_inf (reference: operator.cc:1020
        CheckOpHasNanOrInf + details/nan_inf_utils_detail.cc): host-side
        scan of every fetch and updated state var, error names the var."""
        bad = []
        for n, v in list(zip(fetch_names, fetches)) + \
                list(new_states.items()):
            a = np.asarray(v)
            if np.issubdtype(a.dtype, np.floating) and \
                    not np.all(np.isfinite(a)):
                bad.append(n)
        if bad:
            raise RuntimeError(
                "Operator output contains Inf/Nan (FLAGS_check_nan_inf): "
                "%s" % bad)

    # -- helpers -----------------------------------------------------------
    def _prepare_feed(self, block, feed) -> Dict[str, np.ndarray]:
        """Feed normalization. Fast path: values already on device
        (jax Arrays, e.g. from reader.prefetch_to_device) pass through
        without a host round-trip — dtype casts happen device-side."""
        out = {}
        for name, value in feed.items():
            v = block._find_var_recursive(name)
            want = to_numpy_dtype(v.dtype) if v is not None else None
            if is_on_device(value):
                if want is not None:
                    import jax

                    # compare against the backend's canonical dtype:
                    # with x64 disabled an int64 var holds int32 on
                    # device, and casting back up would only warn
                    want_dev = jax.dtypes.canonicalize_dtype(want)
                    if value.dtype != want_dev:
                        # astype allocates a fresh executor-owned array
                        # — keep it donatable so the step can alias it
                        value = value.astype(want_dev)
                        mark_donatable(value)
                out[name] = value
                continue
            arr = np.asarray(value)
            if want is not None and arr.dtype != want:
                arr = arr.astype(want)
            out[name] = arr
        return out

    @staticmethod
    def _replicate_rows(a, m):
        """Batch-tail bucketing row replication; device arrays
        replicate on device (no host round-trip)."""
        if is_on_device(a):
            import jax.numpy as jnp

            out = jnp.concatenate([a] * m, axis=0)
            mark_donatable(out)  # fresh executor-owned buffer
            return out
        return np.concatenate([a] * m, axis=0)

    # -- elastic training (strategy.elastic; reference reserves the knob
    # at distributed_strategy.proto:301 — here it is the preemption
    # checkpoint/auto-resume loop from fluid/checkpoint.py, wired into
    # every step of the marked program) -------------------------------
    def _elastic_resume(self, program, ecfg, scope):
        import logging

        from . import checkpoint as ckpt

        root = ecfg.get("checkpoint_dir") or "elastic_checkpoints"
        # mark resumed only AFTER the load succeeds (or cleanly finds
        # nothing): a transient load failure must stay retryable, not
        # silently restart from init and rotate out the good checkpoints
        status = ckpt.load_checkpoint(self, root, main_program=program,
                                      scope=scope)
        ecfg["_resumed"] = True
        if status is not None:
            ecfg["_step"] = status.step_no + 1
            logging.getLogger("paddle_tpu.elastic").info(
                "elastic: resumed at step %d from %r", status.step_no,
                root)
        else:
            ecfg.setdefault("_step", 0)

    def _elastic_tick(self, program, ecfg, scope):
        from . import checkpoint as ckpt

        step = ecfg.get("_step", 0)
        ecfg["_step"] = step + 1
        every = int(ecfg.get("save_steps", 100) or 100)
        if (step + 1) % every:
            return
        cp = ecfg.get("_ckpt")
        if cp is None:
            import atexit

            root = ecfg.get("checkpoint_dir") or "elastic_checkpoints"
            cp = ckpt.AsyncCheckpointer(
                root, main_program=program,
                checkpoint_num=int(ecfg.get("max_checkpoints", 3) or 3),
                scope=scope)
            ecfg["_ckpt"] = cp
            # flush the last pending save on normal interpreter exit
            # (the writer is a daemon thread); a failed write raises
            # here or on the next tick via check() — never silently
            atexit.register(cp.close)
        # save_async() calls check() first: a broken checkpoint_dir
        # surfaces as an error on the next tick instead of training for
        # days without preemption safety
        cp.save_async(ckpt.TrainStatus(epoch_no=0, step_no=step))

    def _shard_feeds(self, entry, feed_arrays):
        """Issue (non-blocking) H2D transfers for host arrays; arrays
        already on device pass straight through — the prefetcher put
        them against the program's sharding, so the step consumes them
        without re-putting. When the compiled step donates its feed
        buffers (entry.feed_donate), on-device arrays NOT produced by
        the prefetcher are defensively copied device-side first:
        donation would otherwise invalidate a buffer the caller (e.g. a
        dygraph tensor feeding a static subgraph) still holds."""
        import jax

        def guard(a):
            if entry.feed_donate and not is_donatable(a):
                import jax.numpy as jnp

                return jnp.copy(a)
            return a

        if entry.mesh is None:
            return {n: (guard(a) if is_on_device(a)
                        else jax.numpy.asarray(a))
                    for n, a in feed_arrays.items()}
        from jax.sharding import NamedSharding, PartitionSpec as P

        plan = getattr(entry, "auto_plan", None)
        data_spec = lowering.data_partition_spec(entry.mesh,
                                                 entry.dp_axis)
        out = {}
        for n, a in feed_arrays.items():
            spec = plan.feed_specs.get(n, P()) if plan is not None \
                else data_spec
            target = NamedSharding(entry.mesh, spec)
            if is_on_device(a):
                if getattr(a, "sharding", None) == target:
                    out[n] = guard(a)
                    continue
                a = guard(a)  # reshard below may alias the input
            out[n] = jax.device_put(a, target)
        return out

    def _find_tail_bucket(self, program, feed_arrays, fetch_names, scope):
        """Most-recent cached entry whose batch is an integer multiple of
        this feed's batch: returns (key, multiple, tail_batch,
        names_to_replicate) or None. A feed participates either
        identically (same shape, e.g. a constant side input) or
        replicated (same trailing dims, bucket batch = m * tail batch,
        one shared m). `.lod` offset feeds never bucket — offsets would
        need rebuilding, and ragged data already buckets at the dataset
        tier (fluid/dataset.py)."""
        from ..utils.flags import get_flag

        if not get_flag("FLAGS_batch_tail_bucketing", True):
            return None
        if not self._tail_bucket_safe(program):
            return None
        want_prefix = (program._uid, program._version)
        want_suffix = (tuple(fetch_names), getattr(scope, "_uid", 0))
        names = sorted(feed_arrays)
        for key in reversed(self._cache):
            if key[:2] != want_prefix or key[3:] != want_suffix:
                continue
            cached = {n: (shape, dt) for n, shape, dt in key[2]}
            if sorted(cached) != names:
                continue
            m = None
            rep = set()
            ok = True
            for n in names:
                a = feed_arrays[n]
                cshape, cdt = cached[n]
                if cdt != str(a.dtype):
                    ok = False
                    break
                if cshape == a.shape:
                    continue  # constant side input
                if (n.endswith(".lod") or not a.ndim
                        or cshape[1:] != a.shape[1:] or not a.shape[0]
                        or cshape[0] % a.shape[0]):
                    ok = False
                    break
                this_m = cshape[0] // a.shape[0]
                max_m = int(get_flag("FLAGS_batch_tail_max_multiple", 8)
                            or 8)
                # cap the replication factor: beyond it, compiling the
                # tail's own executable is cheaper than permanently
                # paying m-times the FLOPs per step
                if this_m < 2 or this_m > max_m \
                        or (m is not None and this_m != m):
                    ok = False
                    break
                m = this_m
                rep.add(n)
            if ok and m is not None:
                tails = {feed_arrays[n].shape[0] for n in rep}
                if len(tails) == 1:  # one shared batch axis extent
                    return key, m, tails.pop(), rep
        return None

    def _tail_bucket_safe(self, program):
        """Row replication is exact only for replication-invariant
        programs: a FORWARD op that sum/prod-collapses the batch axis
        (reduce_sum over dim 0 / all dims on a batch-majored var) scales
        by the multiple m, so such programs never bucket. Mean/max/min
        collapses and the grad ops of a mean-type loss are invariant
        (each row appears exactly m times and the 1/B normalization uses
        the padded B)."""
        cached = getattr(program, "_tail_bucket_safe_cache", None)
        if cached is not None and cached[0] == program._version:
            return cached[1]
        unsafe_types = {"reduce_sum", "reduce_prod"}
        # streaming/counting metric ops: replicated rows inflate their
        # per-row counts (histograms, Correct/Total, pair counts) m-fold
        # — in fetches AND in scope-resident accumulator state
        metric_types = {
            "auc", "accuracy", "precision_recall", "mean_iou",
            "detection_map", "positive_negative_pair", "chunk_eval",
            "edit_distance",
        }
        safe = True
        blocks = getattr(program, "blocks", None) or \
            [program.global_block()]
        for block in blocks:
            for op in block.ops:
                if op.type in metric_types:
                    safe = False
                    break
                if op.type not in unsafe_types:
                    continue
                dims = op.attrs.get("dim", op.attrs.get("axis", None))
                if isinstance(dims, int):
                    dims = [dims]
                if dims and 0 not in dims:
                    continue  # reduces non-batch axes only
                for slot_vars in op.input_names.values():
                    for vn in slot_vars:
                        v = block._find_var_recursive(vn)
                        shp = tuple(getattr(v, "shape", ()) or ()) \
                            if v is not None else ()
                        if shp[:1] == (-1,):
                            safe = False
                            break
                    if not safe:
                        break
                if not safe:
                    break
            if not safe:
                break
        program._tail_bucket_safe_cache = (program._version, safe)
        return safe

    def _cache_key(self, program, feed_arrays, fetch_names, scope):
        feed_key = tuple(sorted(
            (n, a.shape, str(a.dtype)) for n, a in feed_arrays.items()))
        # never-reused uids (not id()) so GC'd programs/scopes cannot
        # alias a stale compiled executable
        return (program._uid, program._version, feed_key,
                tuple(fetch_names), getattr(scope, "_uid", 0))

    def feed_sharding(self, program=None):
        """The sharding this program's compiled step expects for its
        feeds — hand it to `reader.prefetch_to_device` so prefetched
        batches land pre-sharded on the right devices. Returns None for
        single-device programs, one NamedSharding for data-parallel
        programs (batch axis over the mesh), or a name->sharding dict
        when an auto-parallel plan exists."""
        from . import compiler

        program = program or framework.default_main_program()
        if isinstance(program, compiler.CompiledProgram):
            program = program._unwrap()
        plan = getattr(program, "_auto_plan", None)
        if plan is not None:
            from jax.sharding import NamedSharding

            return {n: NamedSharding(plan.mesh, s)
                    for n, s in plan.feed_specs.items()}
        mesh = getattr(program, "_mesh", None)
        dp_axis = getattr(program, "_dp_axis", "dp")
        if mesh is None and getattr(program, "_data_parallel", False):
            # same construction compile_block will use — a prefetcher
            # asking for the sharding BEFORE the first compile must not
            # pin a flat mesh on a program the dcn flag would factor
            from ..parallel import env as penv

            mesh = penv.create_hybrid_mesh() or \
                lowering._default_mesh(dp_axis)
            program._mesh = mesh
        if mesh is None:
            return None
        from jax.sharding import NamedSharding

        return NamedSharding(mesh,
                             lowering.data_partition_spec(mesh, dp_axis))

    def _cached_lowerable(self, program, feed, fetch_list, scope):
        """(entry, lowered, mut_avals, feed_avals, ro_avals) for the
        EXECUTOR path's cached executable of this (program, feed
        shapes, fetch list) — run the program once first so the entry
        exists. None when the entry isn't jit-lowered (eager fallback /
        unknown program)."""
        import jax

        program = program or framework.default_main_program()
        from . import compiler

        if isinstance(program, compiler.CompiledProgram):
            program = program._unwrap()
        scope = scope or global_scope()
        fetch_names = [
            f.name if isinstance(f, framework.Variable) else str(f)
            for f in (fetch_list or [])]
        feed_arrays = self._prepare_feed(program.global_block(),
                                         feed or {})
        key = self._cache_key(program, feed_arrays, fetch_names, scope)
        entry = self._cache.get(key)
        if entry is None:
            # dtype-canonicalization can make a host-numpy feed key
            # miss an entry compiled from prefetched device feeds
            # (int64 -> int32 with x64 off): fall back to any cached
            # entry of this program with the same feed names + shapes
            want_shapes = {n: tuple(a.shape)
                           for n, a in feed_arrays.items()}
            for k in reversed(self._cache):
                if k[:2] == key[:2] and k[3:] == key[3:] and \
                        {n: tuple(s) for n, s, _ in k[2]} == want_shapes:
                    key, entry = k, self._cache[k]
                    break
        if entry is None or not hasattr(entry.jitted, "lower"):
            return None
        # feed avals from the CACHED key (the dtypes that executable
        # was actually compiled for), not from this call's arrays
        favals = {n: jax.ShapeDtypeStruct(tuple(s), np.dtype(dt))
                  for n, s, dt in key[2]}
        smut = {n: self._aval_of(scope.find_var(n))
                for n in entry.state_mut_names}
        sro = {n: self._aval_of(scope.find_var(n))
               for n in entry.state_ro_names}
        return (entry, self._lower_entry(entry, favals, smut, sro),
                smut, favals, sro)

    @staticmethod
    def _aval_of(v):
        """value (device array / numpy / python scalar) -> its jit
        argument aval."""
        import jax

        if hasattr(v, "shape") and hasattr(v, "dtype"):
            return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
        a = np.asarray(v)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    @staticmethod
    def _lower_entry(entry, favals, smut, sro):
        """THE (feeds, states_mut, states_ro, seed) lowering call every
        report/pre-flight path shares — one place to change if the jit
        argument shape ever grows."""
        import jax

        return entry.jitted.lower(
            favals, smut, sro, jax.ShapeDtypeStruct((), np.uint32))

    def donation_report(self, program=None, feed=None, fetch_list=None,
                        scope=None):
        """Donation audit via compiled-memory analysis of the EXECUTOR
        path's cached executable (run the program once first so the
        entry exists): verifies FLAGS_tpu_donate_buffers actually
        aliases params/opt-state — and, with
        FLAGS_tpu_donate_feed_buffers, how many feed bytes alias too.
        With the sharded weight update active, also reports the ZeRO-1
        optimizer-state footprint: `opt_state_sharded_vars`,
        `opt_state_logical_bytes` (what the replicated path would hold
        PER replica) vs `opt_state_per_replica_bytes` (~1/N of it).
        Returns {mut_bytes, feed_bytes, alias_bytes, aliases_state,
        feed_donate, ...} or None when the entry isn't jit-lowered
        (eager fallback / unknown program)."""
        got = self._cached_lowerable(program, feed, fetch_list, scope)
        if got is None:
            return None
        return self._donation_report_from(program, *got[:4])

    def _donation_report_from(self, program, entry, lowered, smut,
                              favals):
        """donation_report's body for callers that already hold the
        (entry, lowered, avals) tuple — attribution_report reuses this
        instead of paying a second full trace/lower of the module."""
        ma = self._aot_compile(entry, lowered, smut).memory_analysis()

        def nbytes(avals):
            return sum(int(np.prod(v.shape or (1,))) *
                       np.dtype(v.dtype).itemsize for v in avals.values())

        mut_bytes = nbytes(smut)
        feed_bytes = nbytes(favals)
        alias_bytes = int(getattr(ma, "alias_size_in_bytes", 0))
        sharded = entry.sharded_state or {}
        # shard granularity: the dp axis size — on a hybrid (dcn, ici)
        # mesh that is the INTRA-POD ici size (each pod holds a full
        # copy of the 1/ici shards), not the whole world
        ndev = self._shard_count(entry)
        if sharded:
            # XLA's alias_size_in_bytes is PER DEVICE; a sharded state
            # var occupies only padded/N bytes there — shrink the
            # donation target accordingly so the audit compares like
            # with like
            for info in sharded.values():
                if info.name in smut:
                    mut_bytes -= (info.padded - info.padded // ndev) \
                        * info.dtype.itemsize
        out = {
            "mut_bytes": mut_bytes,
            "feed_bytes": feed_bytes,
            "alias_bytes": alias_bytes,
            "aliases_state": alias_bytes >= mut_bytes,
            "feed_donate": bool(entry.feed_donate),
        }
        out["opt_state_sharded_vars"] = len(sharded)
        if sharded:
            out["opt_state_logical_bytes"] = sum(
                info.numel * info.dtype.itemsize
                for info in sharded.values())
            out["opt_state_per_replica_bytes"] = sum(
                (info.padded // ndev) * info.dtype.itemsize
                for info in sharded.values())
        plan = self._shard_plan_of(program)
        if plan is not None and getattr(plan, "buckets", ()):
            # bucketed grad exchange: the transient per-replica shard
            # buffers are one per bucket — SUM over buckets (there is
            # no single flat shard buffer whose scope var could be
            # read), logical = the pre-scatter padded grads
            out["grad_bucket_count"] = len(plan.buckets)
            out["grad_bucket_logical_bytes"] = sum(
                b.nbytes for b in plan.buckets)
            out["grad_bucket_per_replica_bytes"] = sum(
                b.shard_numel(ndev) * b.dtype.itemsize
                for b in plan.buckets)
            # ZeRO-2 gradient-lifetime model: full-size grad buffers die
            # bucket-by-bucket (each bucket's only full-value consumer
            # is its own reduce-scatter, verified statically by the
            # zero2-lifetimes checker), so at most ONE bucket's full
            # grads coexist with the accumulated 1/N shards — vs the
            # replicated path where every full grad is live at once
            out["grad_peak_per_replica_bytes"] = (
                max(b.nbytes for b in plan.buckets)
                + out["grad_bucket_per_replica_bytes"])
            out["grad_replicated_peak_bytes"] = \
                out["grad_bucket_logical_bytes"]
        # mixed precision (AMP level O2): live params in the 16-bit
        # compute dtype + fp32 masters — ZeRO-sharded masters cost
        # padded/N fp32 bytes per replica, so per-replica param state is
        # ~(2 + 4/N) bytes/elem vs fp32 DP's 4 (halved for N >= 4)
        prog = program or framework.default_main_program()
        from . import compiler as _compiler

        if isinstance(prog, _compiler.CompiledProgram):
            prog = prog._unwrap()
        amp_masters = dict(getattr(prog, "_amp_master_of", None) or {})
        if amp_masters:
            block = prog.global_block()
            p_bytes = m_rep = m_logical = 0
            for p, m in amp_masters.items():
                pv = block._find_var_recursive(p)
                if pv is None:
                    continue
                numel = int(np.prod(tuple(pv.shape) or (1,)))
                p_bytes += numel * np.dtype(
                    to_numpy_dtype(pv.dtype)).itemsize
                m_logical += numel * 4
                info = sharded.get(m)
                m_rep += ((info.padded // ndev) * 4 if info is not None
                          else numel * 4)
            out["param_bf16_bytes"] = p_bytes
            out["param_master_bytes"] = m_rep
            out["param_fp32_replicated_bytes"] = m_logical
            out["param_masters_sharded"] = sum(
                1 for m in amp_masters.values() if m in sharded)
        # fp8 tier (amp_dtype="float8_e4m3"): the qdq sites keep the
        # bf16 carrier in HBM, so the e4m3 operand bytes are a MODELED
        # lane — what a native-fp8 layout would hold at the dot sites —
        # reported beside the measured scale-state footprint
        fp8_cfg = getattr(prog, "_amp_fp8", None)
        if fp8_cfg:
            hist_len = int(fp8_cfg.get("amax_history_len", 16))
            sites_in = fp8_cfg.get("inputs", {}) or {}
            sites_gr = fp8_cfg.get("grads", {}) or {}
            out["fp8_site_inputs"] = len(sites_in)
            out["fp8_site_grads"] = len(sites_gr)
            out["fp8_state_bytes"] = (len(sites_in) + len(sites_gr)) \
                * (hist_len + 1) * 4
            block = prog.global_block()
            carrier = modeled = 0
            for n in sites_in:
                v = block._find_var_recursive(n)
                if v is None:
                    continue
                numel = int(np.prod(tuple(v.shape) or (1,)))
                carrier += numel * np.dtype(
                    to_numpy_dtype(v.dtype)).itemsize
                modeled += numel  # e4m3: 1 byte/elem
            out["fp8_operand_carrier_bytes"] = carrier
            out["fp8_operand_bytes_modeled"] = modeled
        return out

    @staticmethod
    def _shard_count(entry):
        """ZeRO shard granularity of a cached entry: the dp-axis size
        (= intra-pod ici size on a hybrid mesh), 1 off-mesh."""
        if entry.mesh is None:
            return 1
        if entry.dp_axis in entry.mesh.shape:
            return int(entry.mesh.shape[entry.dp_axis])
        return int(np.prod(
            [entry.mesh.shape[a] for a in entry.mesh.axis_names]))

    @staticmethod
    def _aot_compile(entry, lowered, smut):
        """AOT-compile once per cache entry: donation_report and
        overlap_report both need the compiled artifact, and XLA does
        not memoize Lowered.compile() — without this, every report
        call recompiles the whole module. Keyed on the live state
        avals: a checkpoint restore writes LOGICAL-shaped arrays back
        into scope (the next step reconverts), so `lowered` can differ
        from the memoized compile — recompile rather than hand back a
        stale artifact."""
        key = tuple(sorted((n, tuple(a.shape), str(a.dtype))
                           for n, a in smut.items()))
        if entry.aot_compiled is None or entry.aot_compiled[0] != key:
            entry.aot_compiled = (key, lowered.compile())
        return entry.aot_compiled[1]

    @staticmethod
    def _shard_plan_of(program):
        program = program or framework.default_main_program()
        from . import compiler

        if isinstance(program, compiler.CompiledProgram):
            program = program._unwrap()
        return getattr(program, "_shard_plan", None)

    def collective_report(self, program=None, feed=None, fetch_list=None,
                          scope=None):
        """Per-collective byte accounting for the cached executable
        (run the program once first): parses the lowered StableHLO for
        all_reduce / reduce_scatter / all_gather ops and models ring
        ICI bytes — offline evidence that the sharded weight update
        actually halves the grad+param exchange (see
        lowering.collective_byte_census). With bucketed collectives
        (FLAGS_tpu_comm_bucket_mb > 0) the census also carries the
        per-bucket byte breakdown — per-replica totals SUM the buckets
        (there is no single flat shard buffer to read). None when not
        jit-lowered."""
        got = self._cached_lowerable(program, feed, fetch_list, scope)
        if got is None:
            return None
        entry, lowered = got[0], got[1]
        ndev = 1
        if entry.mesh is not None:
            ndev = int(np.prod([entry.mesh.shape[a]
                                for a in entry.mesh.axis_names]))
        from ..parallel import env as penv

        hier = penv.mesh_hierarchy(entry.mesh)
        census = lowering.collective_byte_census(
            lowered.as_text(), ndev,
            ici_size=(hier[3] if hier is not None else None),
            mp_size=(hier.mp_size if hier is not None else None))
        plan = self._shard_plan_of(program)
        shards = self._shard_count(entry)
        if plan is not None and getattr(plan, "buckets", ()):
            # the cap the plan was built under, not the live flag (a
            # flag change after compile must not contradict `buckets`)
            census["bucket_cap_mb"] = getattr(
                plan, "bucket_cap", 0) / float(1 << 20)
            census["buckets"] = [{
                "index": b.index,
                "grads": len(b.entries),
                "dtype": str(b.dtype),
                "bytes": b.nbytes,
                "shard_bytes": b.shard_numel(shards) * b.dtype.itemsize,
            } for b in plan.buckets]
            census["bucket_bytes_total"] = sum(
                b.nbytes for b in plan.buckets)
        # fp8 tier: the grad exchange crosses ICI in the bf16 carrier
        # dtype (measured above); an e5m2 grad wire would carry
        # 1 byte/elem — a MODELED lane, labeled as such, beside the
        # measured census
        prog = program or framework.default_main_program()
        from . import compiler as _compiler

        if isinstance(prog, _compiler.CompiledProgram):
            prog = prog._unwrap()
        if getattr(prog, "_amp_fp8", None):
            itemsize = {"bfloat16": 2, "float16": 2}.get(
                str(getattr(prog, "_amp_dtype", "float32")), 4)
            grad_tensor = grad_wire = 0
            for kind in ("all_reduce", "reduce_scatter"):
                rec = census.get(kind)
                if isinstance(rec, dict):
                    grad_tensor += rec.get("tensor_bytes", 0)
                    grad_wire += rec.get("ici_bytes", 0)
            census["fp8_wire"] = {
                "modeled": True,
                "carrier_itemsize": int(itemsize),
                "grad_sync_wire_bytes": grad_wire,
                "grad_sync_wire_bytes_e5m2": grad_wire // itemsize,
                "grad_sync_tensor_bytes": grad_tensor,
            }
        return census

    def attribution_report(self, program=None, feed=None,
                           fetch_list=None, scope=None, topk=10):
        """Per-op HBM attribution of the cached executable (run the
        program once first): decomposes the compiled step's
        memory_analysis() peak into buffer classes (feed / param /
        master / opt_state / grad_bucket / state_other / activation)
        per framework op and layer via the provenance markers the
        lowering stamped (FLAGS_tpu_op_provenance), maps every
        collective in the lowered module back to its fluid op / bucket
        / gradient, and cross-checks the class totals against
        donation_report EXACTLY. See
        paddle_tpu/observability/attribution.py; bench.py emits this as
        the "attribution" block and `tools/perf_analysis.py
        --attribution` writes artifacts/attribution.json. None when not
        jit-lowered."""
        got = self._cached_lowerable(program, feed, fetch_list, scope)
        if got is None:
            return None
        entry, lowered, smut, favals, sro = got
        from ..observability import attribution as _attr

        prog = program or framework.default_main_program()
        from . import compiler as _compiler

        if isinstance(prog, _compiler.CompiledProgram):
            prog = prog._unwrap()
        compiled = self._aot_compile(entry, lowered, smut)
        state_avals = dict(smut)
        state_avals.update(sro)
        # flat jit argument order (feeds, mut state, ro state, seed;
        # dict pytrees flatten sorted by key) — seeds the optimized
        # HLO pass's parameter->var inheritance
        arg_names = (sorted(favals) + sorted(smut) + sorted(sro)
                     + ["<seed>"])
        rep = _attr.build_report(
            prog, prog.global_block(), self._shard_plan_of(program),
            self._shard_count(entry), favals, state_avals,
            ma=compiled.memory_analysis(),
            optimized_hlo=compiled.as_text(),
            stablehlo_asm=_attr.stablehlo_debug_asm(lowered),
            topk=topk, arg_names=arg_names)
        rep["cross_check"] = _attr.cross_check_donation(
            rep, self._donation_report_from(program, entry, lowered,
                                            smut, favals))
        return rep

    def _hbm_preflight(self, program, entry, feed_arrays, states_mut,
                       states_ro, scope):
        """OOM pre-flight (FLAGS_tpu_hbm_budget_mb; runs once per fresh
        compile, BEFORE the first dispatch): AOT-compile the entry,
        model peak HBM (memory_analysis + the input pipeline's
        prefetched feed buffers) and raise a structured
        HbmBudgetExceeded naming the top consumers when it exceeds the
        budget — a pre-dispatch failure with a named culprit instead of
        an opaque RESOURCE_EXHAUSTED mid-run."""
        from ..observability import attribution as _attr

        budget = _attr.budget_bytes()
        if budget is None or not hasattr(entry.jitted, "lower"):
            return
        favals = {n: self._aval_of(a) for n, a in feed_arrays.items()}
        smut = {n: self._aval_of(v) for n, v in states_mut.items()}
        sro = {n: self._aval_of(v) for n, v in states_ro.items()}
        lowered = self._lower_entry(entry, favals, smut, sro)
        ma = self._aot_compile(entry, lowered, smut).memory_analysis()
        feed_bytes = sum(
            int(np.prod(a.shape or (1,))) * np.dtype(a.dtype).itemsize
            for a in favals.values())
        predicted = _attr.predicted_peak_bytes(ma, feed_bytes)
        if predicted <= budget:
            return
        prog = program
        from . import compiler as _compiler

        if isinstance(prog, _compiler.CompiledProgram):
            prog = prog._unwrap()
        breakdown = _attr.static_breakdown(
            prog, prog.global_block(), self._shard_plan_of(program),
            self._shard_count(entry), feed_arrays=feed_arrays,
            state_names=list(states_mut) + list(states_ro),
            scope=scope)
        top = breakdown["top_consumers"]
        from .. import observability as _obs

        try:
            _obs.registry().event(
                "hbm_preflight", verdict="exceeded",
                predicted_bytes=int(predicted),
                budget_bytes=int(budget),
                top_consumer=top[0]["name"] if top else None)
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass
        raise _attr.HbmBudgetExceeded(predicted, budget, top)

    def overlap_report(self, program=None, feed=None, fetch_list=None,
                       scope=None):
        """Collective/compute overlap audit of the cached executable's
        OPTIMIZED (scheduled) HLO — can the grad reduce-scatters start
        while backward compute is still outstanding, or are they fenced
        at the end? See lowering.collective_overlap_audit for the
        model; `tools/perf_analysis.py --overlap-audit` drives this on
        the BERT-tiny program and bench.py emits it as "overlap". None
        when not jit-lowered."""
        got = self._cached_lowerable(program, feed, fetch_list, scope)
        if got is None:
            return None
        entry, lowered, smut = got[0], got[1], got[2]
        rep = lowering.collective_overlap_audit(
            self._aot_compile(entry, lowered, smut).as_text())
        plan = self._shard_plan_of(program)
        if plan is not None:
            rep["n_buckets"] = len(getattr(plan, "buckets", ()))
        return rep

    def close(self):
        for comm in getattr(self, "_ps_comms", {}).values():
            comm.complete()
        if hasattr(self, "_ps_comms"):
            self._ps_comms.clear()
        self._cache.clear()

    # dataset-training entry points (reference: executor.py:1454) are
    # provided by the trainer runtime in paddle_tpu.fluid.trainer
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        from .trainer import train_from_dataset as _tfd

        return _tfd(self, program, dataset, scope, fetch_list, print_period)

    def infer_from_dataset(self, *args, **kwargs):
        return self.train_from_dataset(*args, **kwargs)
