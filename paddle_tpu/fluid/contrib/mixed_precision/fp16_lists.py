"""AMP op lists (reference:
`python/paddle/fluid/contrib/mixed_precision/fp16_lists.py:28`).

On TPU the 16-bit type is bfloat16: same exponent range as fp32, so the
white list can be broader and dynamic loss scaling is unnecessary (it IS
wired — lowering._run_loss_scaled_post — for `amp_dtype="float16"`).
How the lists drive the trace-time cast policy, the fp32 master-weight
layout and its ZeRO sharding: `paddle_tpu/parallel/README.md`
("Mixed precision & ZeRO-2")."""
from __future__ import annotations

# MXU-bound ops: run in bf16
white_list = {
    "conv2d", "depthwise_conv2d", "conv2d_transpose", "matmul", "matmul_v2",
    "mul",
    # fp32-accumulating inside (preferred_element_type), so bf16 inputs
    # are safe despite the loss epilogue
    "fused_linear_softmax_xent",
}

# numerically sensitive: force fp32
black_list = {
    "softmax_with_cross_entropy", "cross_entropy", "exp", "log",
    "mean", "sum", "reduce_mean", "reduce_sum", "softmax",
    "sigmoid_cross_entropy_with_logits", "layer_norm", "batch_norm",
}

# neutral: follow inputs
gray_list = {
    "elementwise_add", "elementwise_mul", "elementwise_sub",
    "elementwise_div", "relu", "gelu", "tanh", "sigmoid", "dropout",
    "pool2d", "transpose2", "reshape2", "concat", "split", "slice",
    "scale",
}

# fp8 tier (amp_dtype="float8_e4m3"): the NARROW subset of the white
# list whose operands additionally pass through an e4m3
# quantize-dequantize at the per-tensor delayed scale (grad cotangents
# through e5m2). Deliberately excludes fused_linear_softmax_xent — its
# fused loss epilogue is the numerically sensitive part the fusion
# protects. bf16 stays the carrier compute dtype everywhere else.
fp8_white_list = {
    "conv2d", "depthwise_conv2d", "conv2d_transpose", "matmul",
    "matmul_v2", "mul",
}


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None, custom_fp8_white_list=None):
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        self.gray_list = set(gray_list)
        self.fp8_white_list = set(fp8_white_list)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
            self.black_list -= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)
            self.fp8_white_list -= set(custom_black_list)
        if custom_fp8_white_list:
            # fp8 sites must also be white-list (bf16 carrier) sites:
            # the qdq rides on top of the 16-bit cast policy
            self.fp8_white_list |= set(custom_fp8_white_list)
            self.white_list |= set(custom_fp8_white_list)
            self.black_list -= set(custom_fp8_white_list)
        self.black_varnames = set(custom_black_varnames or [])
