"""AMP program rewrites (reference:
`python/paddle/fluid/contrib/mixed_precision/fp16_utils.py`: cast
insertion + master-weight creation for OptimizerWithMixedPrecision).

TPU-native split of responsibilities:

- the white/black-list CASTS are applied at trace time by
  `fluid/lowering._apply_amp_casts` (XLA fuses them; no cast ops clutter
  the IR) — see `paddle_tpu/parallel/README.md` "Mixed precision &
  ZeRO-2";
- THIS module performs the two rewrites that must be visible in the IR
  because they change the program's state contract:

  1. ``rewrite_master_weights``: the live parameters become the compute
     dtype (bf16/fp16) while an fp32 MASTER copy (``<param>@MASTER``)
     becomes the value the optimizer op updates; a trailing ``cast`` op
     re-derives the live param from the updated master. Under the
     ZeRO-1 plan (`parallel/sharded_update.plan_sharded_update`) the
     masters live as P(dp)-sharded flat buffers across steps exactly
     like the moments, so per-replica param state is
     ``numel*2 (live bf16) + numel*4/N (master shard)`` instead of
     ``numel*4`` — and the param all-gather moves half the ICI bytes
     (it carries the bf16 cast of the updated shard).
  2. ``wire_dynamic_loss_scaling`` (fp16 only — bf16 shares fp32's
     exponent range and needs none by design): persistable scale /
     good-step / bad-step state vars plus a ``dynamic_loss_scaling``
     attr on the backward op; `fluid/lowering._run_loss_scaled_post`
     runs the whole post-backward section under ``lax.cond`` on the
     psum'd finite check and steps the scale state machine.
"""
from __future__ import annotations

from ... import framework
from ...framework import grad_var_name, unique_name
from ....core.types import normalize_dtype

MASTER_SUFFIX = "@MASTER"


def master_name(param_name: str) -> str:
    return param_name + MASTER_SUFFIX


def rewrite_master_weights(program, startup_program, compute_dtype):
    """Rewire every optimizer op's Param/ParamOut to an fp32 master var,
    flip the live params (and their grads) to `compute_dtype`, and
    append one ``cast`` op per param re-deriving the live value from the
    updated master. Returns {param_name: master_name}.

    Startup contract: the initializer op still fills the EXACT fp32
    init value; the master is assigned from it BEFORE the live param is
    down-cast — so the fp32 master starts bit-identical to a non-AMP
    run's param, and the live param is its 16-bit cast.
    """
    compute_dtype = normalize_dtype(compute_dtype)
    block = program.global_block()
    bwd_idx = next((i for i, op in enumerate(block.ops)
                    if op.type == "backward"), None)
    post = block.ops[bwd_idx + 1:] if bwd_idx is not None else block.ops

    master_of = {}
    for op in post:
        params = op.input_names.get("Param", [])
        pouts = op.output_names.get("ParamOut", [])
        if not params or not pouts:
            continue
        for i, p in enumerate(params):
            if p.endswith(MASTER_SUFFIX):
                continue
            v = block._find_var_recursive(p)
            if v is None or str(v.dtype) != "float32" \
                    or not getattr(v, "persistable", False):
                continue
            m = master_of.get(p)
            if m is None:
                m = _create_master(program, startup_program, v,
                                   compute_dtype)
                master_of[p] = m
            op.input_names["Param"][i] = m
            for j, po in enumerate(op.output_names["ParamOut"]):
                if po == p:
                    op.output_names["ParamOut"][j] = m

    # one trailing cast per param: the live 16-bit value is re-derived
    # from the updated fp32 master. Marked so the ZeRO planner can prove
    # this is the master's ONLY reader outside its optimizer op (it
    # becomes a shard-space cast whose output all-gathers in 16 bits).
    for p, m in master_of.items():
        block.append_op(
            type="cast", inputs={"X": [m]}, outputs={"Out": [p]},
            attrs={"in_dtype": "float32", "out_dtype": str(compute_dtype),
                   "__amp_param_cast__": True})
    if master_of:
        program._version += 1
    return master_of


def _create_master(program, startup_program, v, compute_dtype):
    block = program.global_block()
    m = master_name(v.name)
    mv = block.create_var(name=m, shape=list(v.shape), dtype="float32",
                          persistable=True)
    mv.stop_gradient = True
    if startup_program is not None:
        sb = startup_program.global_block()
        if sb.has_var(v.name):
            sb.create_var(name=m, shape=list(v.shape), dtype="float32",
                          persistable=True)
            # master = the exact fp32 init; then the live param becomes
            # its 16-bit cast (order matters: assign reads fp32)
            sb.append_op(type="assign", inputs={"X": [v.name]},
                         outputs={"Out": [m]})
            sb.append_op(
                type="cast", inputs={"X": [v.name]},
                outputs={"Out": [v.name]},
                attrs={"in_dtype": "float32",
                       "out_dtype": str(compute_dtype),
                       "__amp_param_cast__": True})
    # flip the live param and its grad to the compute dtype — the vjp
    # binds gradients at the param's dtype (lowering), so grads are
    # 16-bit too and the grad reduce-scatter bytes halve with the params
    v.dtype = compute_dtype
    g = block._find_var_recursive(grad_var_name(v.name))
    if g is not None:
        g.dtype = compute_dtype
    return m


def wire_dynamic_loss_scaling(program, startup_program, cfg):
    """Create the persistable loss-scale state (scale fp32, good/bad
    step counters int32) and attach the ``dynamic_loss_scaling`` attr to
    the backward op. The state rides the backward op's input/output
    slots so `lowering.analyze_block` threads it as mutable scope state
    — it persists across steps and through checkpoint save/restore like
    any other optimizer state. Returns the attr dict (or None when the
    program has no backward section)."""
    block = program.global_block()
    bop = next((op for op in block.ops if op.type == "backward"), None)
    if bop is None:
        return None
    sb = startup_program.global_block() if startup_program is not None \
        else None

    def state(stem, dtype, value):
        v = block.create_var(name=unique_name(stem), shape=[1],
                             dtype=dtype, persistable=True)
        v.stop_gradient = True
        if sb is not None:
            sb.create_var(name=v.name, shape=[1], dtype=dtype,
                          persistable=True)
            sb.append_op(type="fill_constant", outputs={"Out": [v.name]},
                         attrs={"shape": [1], "dtype": dtype,
                                "value": float(value)})
        return v.name

    dls = {
        "scale": state("loss_scaling", "float32",
                       cfg["init_loss_scaling"]),
        "good": state("num_good_steps", "int32", 0),
        "bad": state("num_bad_steps", "int32", 0),
        "incr_every_n_steps": int(cfg["incr_every_n_steps"]),
        "decr_every_n_nan_or_inf": int(cfg["decr_every_n_nan_or_inf"]),
        "incr_ratio": float(cfg["incr_ratio"]),
        "decr_ratio": float(cfg["decr_ratio"]),
    }
    bop.attrs["dynamic_loss_scaling"] = dls
    extra = [dls["scale"], dls["good"], dls["bad"]]
    bop.input_names["LossScaleState"] = list(extra)
    bop.output_names["LossScaleState"] = list(extra)
    program._version += 1
    return dls


#: e4m3 / e5m2 saturation values (finite maxima of the two fp8 formats)
FP8_E4M3_MAX = 448.0
FP8_E5M2_MAX = 57344.0

FP8_SCALE_SUFFIX = "@FP8_SCALE"
FP8_HIST_SUFFIX = "@FP8_AMAX_HIST"
FP8_GRAD_SCALE_SUFFIX = "@FP8_GRAD_SCALE"
FP8_GRAD_HIST_SUFFIX = "@FP8_GRAD_HIST"


def wire_fp8_delayed_scaling(program, startup_program, amp_lists,
                             amax_history_len=16):
    """fp8 tier (amp_dtype="float8_e4m3"): create the per-tensor
    delayed-scaling state and attach the ``fp8_delayed_scaling`` attr to
    the backward op.

    For every fp8 white-list op in the FORWARD section, each float
    input var gets an e4m3 pair — ``<var>@FP8_AMAX_HIST`` (fp32,
    [amax_history_len], the rolling abs-max window) and
    ``<var>@FP8_SCALE`` (fp32, [1], ``E4M3_MAX / max(hist)``, 1.0 while
    the window is empty) — and each float output var gets the e5m2
    GRAD pair (``@FP8_GRAD_HIST`` / ``@FP8_GRAD_SCALE``) scaling the
    cotangent that flows back through the op. The state rides the
    backward op's ``Fp8ScaleState`` input/output slots exactly like
    PR 6's ``LossScaleState``, so `lowering.analyze_block` threads it
    as mutable scope state: it persists across steps and through
    checkpoint save/restore (incl. elastic re-shard — the vars are
    replicated [H]/[1] scalars, never ZeRO-sharded) like any other
    optimizer state. The lowering's trace-time qdq sites read the
    scales, observe this step's abs-max (fwd via env taps, grads via
    the vjp-cotangent tap idiom), and the post-step update rolls the
    history — pmax'd over every live mesh axis so the scale stays
    replica-uniform under DP/DCN/TP.

    Returns the attr dict (or None when the program has no backward
    section or no fp8-eligible site)."""
    block = program.global_block()
    bop = next((op for op in block.ops if op.type == "backward"), None)
    if bop is None:
        return None
    bwd_idx = block.ops.index(bop)
    sb = startup_program.global_block() if startup_program is not None \
        else None

    def state(base, suffix, shape, value):
        name = base + suffix
        v = block.create_var(name=name, shape=list(shape),
                             dtype="float32", persistable=True)
        v.stop_gradient = True
        if sb is not None and not sb.has_var(name):
            sb.create_var(name=name, shape=list(shape), dtype="float32",
                          persistable=True)
            sb.append_op(type="fill_constant", outputs={"Out": [name]},
                         attrs={"shape": list(shape), "dtype": "float32",
                                "value": float(value)})
        return name

    fp8_ops = set(getattr(amp_lists, "fp8_white_list", ()) or ())
    float_dtypes = ("float32", "bfloat16", "float16")
    inputs, grads = {}, {}
    for op in block.ops[:bwd_idx]:
        if op.type not in fp8_ops:
            continue
        for n in op.input_arg_names:
            if n in inputs:
                continue
            v = block._find_var_recursive(n)
            if v is None or str(v.dtype) not in float_dtypes:
                continue
            inputs[n] = {
                "hist": state(n, FP8_HIST_SUFFIX,
                              [int(amax_history_len)], 0.0),
                "scale": state(n, FP8_SCALE_SUFFIX, [1], 1.0),
            }
        for n in op.output_arg_names:
            if n in grads:
                continue
            v = block._find_var_recursive(n)
            if v is None or str(v.dtype) not in float_dtypes:
                continue
            grads[n] = {
                "hist": state(n, FP8_GRAD_HIST_SUFFIX,
                              [int(amax_history_len)], 0.0),
                "scale": state(n, FP8_GRAD_SCALE_SUFFIX, [1], 1.0),
            }
    if not inputs and not grads:
        return None

    cfg = {
        "inputs": inputs,
        "grads": grads,
        "amax_history_len": int(amax_history_len),
        "fwd_max": FP8_E4M3_MAX,
        "grad_max": FP8_E5M2_MAX,
        "ops": sorted(fp8_ops),
    }
    bop.attrs["fp8_delayed_scaling"] = cfg
    extra = [s[k] for group in (inputs, grads)
             for s in group.values() for k in ("hist", "scale")]
    bop.input_names["Fp8ScaleState"] = list(extra)
    bop.output_names["Fp8ScaleState"] = list(extra)
    program._version += 1
    return cfg


class EagerMasterWeightOptimizer:
    """Dygraph fp32-master shim (`hapi.Model.prepare(amp_level='O2')`):
    the live parameters stay in the 16-bit compute dtype; each step the
    inner optimizer updates an fp32 master copy (kept here, keyed by
    param name) and the live param is rebound to the updated master's
    16-bit cast — so update precision never degrades to bf16/fp16
    round-off while forward/backward run on 16-bit params."""

    def __init__(self, optimizer):
        self._opt = optimizer
        self._masters = {}
        # the exact live array object this wrapper last assigned per
        # param: any external reassignment (Model.load, set_state_dict,
        # a user _assign_raw) replaces it with a DIFFERENT object, which
        # invalidates the cached master — otherwise the next step would
        # swap the stale pre-load master back over the loaded weights
        self._last_live = {}

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        import jax.numpy as jnp

        params = parameter_list if parameter_list is not None \
            else getattr(self._opt, "_parameter_list", None) or []
        # grads must be taken against the LIVE 16-bit values; they are
        # stored on the param object and survive the value swap below
        if not getattr(loss, "_backward_ran", False):
            loss.backward()
        swapped = []
        for p in params:
            val = p._value()
            if not jnp.issubdtype(val.dtype, jnp.floating) \
                    or val.dtype == jnp.float32:
                continue
            m = self._masters.get(p.name)
            if m is None or tuple(m.shape) != tuple(val.shape) \
                    or self._last_live.get(p.name) is not val:
                m = val.astype(jnp.float32)
                # masters shard over the mesh like the eager optimizer
                # accumulators (P(ici) dim-0, divisibility-gated):
                # FLAGS_tpu_sharded_update + an active global mesh move
                # the fp32 copy's memory off every replica, and XLA
                # partitions the master update against the layout
                from ....parallel.sharded_update import \
                    eager_accumulator_sharding

                sh = eager_accumulator_sharding(tuple(m.shape))
                if sh is not None:
                    import jax

                    m = jax.device_put(m, sh)
            swapped.append((p, val.dtype))
            p._assign_raw(m)
        try:
            result = self._opt.minimize(
                loss, parameter_list=parameter_list,
                no_grad_set=no_grad_set)
        finally:
            for p, low in swapped:
                new_master = p._value()
                self._masters[p.name] = new_master
                live = new_master.astype(low)
                self._last_live[p.name] = live
                p._assign_raw(live)
        return result
