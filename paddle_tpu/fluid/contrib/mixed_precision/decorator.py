"""AMP decorator (reference:
`python/paddle/fluid/contrib/mixed_precision/decorator.py:27-218`:
OptimizerWithMixedPrecision rewrites the program inserting casts + dynamic
loss scaling via amp_check_finite_and_scale).

TPU-native: `decorate()` marks the program with a white/black-list
compute policy that the lowering applies per-op at trace time (white
list ops run on the MXU in the 16-bit compute dtype; black list ops
compute in fp32) AND — at amp_level "O2", the default — rewrites the
program for **fp32 master weights**: live params (and their grads)
become the compute dtype, every optimizer op updates an fp32
``<param>@MASTER`` var, and a trailing cast re-derives the live param
(fp16_utils.rewrite_master_weights). Under the ZeRO-1 plan
(`parallel/sharded_update`), the masters live SHARDED as P(dp) flat
buffers across steps like the moments, the optimizer consumes the
reduce-scattered 16-bit grad shard, and the per-bucket all-gather
carries the 16-bit cast of the updated shard — so param HBM and
all-gather ICI bytes both halve relative to fp32 data parallelism.
Full catalog + knobs: `paddle_tpu/parallel/README.md`
("Mixed precision & ZeRO-2").

Loss scaling: bfloat16 shares fp32's exponent range, so bf16 (the
default `amp_dtype`) needs none by design. With `amp_dtype="float16"`,
dynamic loss scaling is wired for real: the loss cotangent is scaled by
a persistable scale var, gradients are finite-checked (psum'd across
the dp axis so the predicate is replica-uniform) and unscaled, the
whole weight update runs under a ``lax.cond`` that SKIPS it on
overflow, and the scale grows every `incr_every_n_steps` clean steps /
decays after `decr_every_n_nan_or_inf` overflows
(fluid/lowering._run_loss_scaled_post). The scale state persists in the
Scope and through checkpoint save/restore like any optimizer state.

`FLAGS_tpu_amp_level` overrides the decorate-time level ("O0" is the
kill switch: decorated programs lower exactly like undecorated ones).
"""
from __future__ import annotations

from ... import framework
from .fp16_lists import AutoMixedPrecisionLists

#: accepted spellings of the fp8 training tier's amp_dtype
_FP8_DTYPE = "float8_e4m3"
_FP8_ALIASES = ("float8_e4m3", "float8_e4m3fn", "float8", "fp8")


def _normalize_amp_dtype(amp_dtype):
    """bf16/fp16 via the canonical normalizer; fp8 spellings collapse to
    "float8_e4m3" (the forward operand format — grads always e5m2)."""
    if isinstance(amp_dtype, str) and \
            amp_dtype.lower() in _FP8_ALIASES:
        return _FP8_DTYPE
    from ....core.types import normalize_dtype

    d = normalize_dtype(amp_dtype)
    if d not in ("bfloat16", "float16"):
        raise ValueError(
            "amp_dtype must be 'bfloat16', 'float16' or 'float8_e4m3', "
            "got %r" % (amp_dtype,))
    return d


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2.**15,
                 use_dynamic_loss_scaling=True, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0,
                 decr_ratio=0.8, amp_dtype="bfloat16", amp_level="O2",
                 fp8_amax_history_len=16):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scaling = float(init_loss_scaling)
        self._use_dynamic_loss_scaling = bool(use_dynamic_loss_scaling)
        self._incr_every_n_steps = int(incr_every_n_steps)
        self._decr_every_n_nan_or_inf = int(decr_every_n_nan_or_inf)
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._amp_dtype = _normalize_amp_dtype(amp_dtype)
        self._fp8_amax_history_len = int(fp8_amax_history_len)
        if amp_level not in ("O0", "O1", "O2"):
            raise ValueError("amp_level must be one of O0/O1/O2, got %r"
                             % (amp_level,))
        self._amp_level = amp_level
        self._master_of = {}
        self._scale_state = None
        self._fp8_state = None

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def get_loss_scaling(self):
        """Current loss scale: the live scope value under dynamic
        scaling, the static init value otherwise."""
        if self._scale_state is not None:
            from ....core.scope import global_scope
            import numpy as np

            v = global_scope().find_var(self._scale_state["scale"])
            if v is not None:
                return float(np.asarray(v).reshape(-1)[0])
        return self._loss_scaling

    def get_master_weights(self):
        """{param_name: master_var_name} after minimize() at level O2."""
        return dict(self._master_of)

    def backward(self, loss, **kwargs):
        return self._optimizer.backward(loss, **kwargs)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def _effective_level(self):
        from ....utils.flags import get_flag

        flag = str(get_flag("FLAGS_tpu_amp_level", "") or "").upper()
        if flag in ("O0", "O1", "O2"):
            return flag
        return self._amp_level

    def _effective_dtype(self):
        """FLAGS_tpu_amp_dtype override, else the decorate-time dtype.
        The flag is the fp8 kill switch: "bfloat16" makes a
        fp8-decorated program lower EXACTLY like the bf16 one (no
        scaling state, byte-identical HLO)."""
        from ....utils.flags import get_flag

        flag = str(get_flag("FLAGS_tpu_amp_dtype", "") or "")
        if flag:
            return _normalize_amp_dtype(flag)
        return self._amp_dtype

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        level = self._effective_level()
        if level == "O0":  # kill switch: lower exactly like undecorated
            return self._optimizer.minimize(loss, startup_program,
                                            parameter_list, no_grad_set)
        amp_dtype = self._effective_dtype()
        fp8 = amp_dtype == _FP8_DTYPE
        # fp8 rides a bf16 carrier: the white/black-list cast policy,
        # fp32 masters and collectives are the EXACT bf16 lowering; the
        # e4m3/e5m2 quantize-dequantize sites stack on top at the
        # fp8-white-list ops only
        compute_dtype = "bfloat16" if fp8 else amp_dtype
        program._amp = True
        program._amp_lists = self._amp_lists
        program._amp_dtype = compute_dtype
        result = self._optimizer.minimize(loss, startup_program,
                                          parameter_list, no_grad_set)
        startup = startup_program or framework.default_startup_program()
        from .fp16_utils import (rewrite_master_weights,
                                 wire_dynamic_loss_scaling,
                                 wire_fp8_delayed_scaling)

        if level == "O2":
            self._master_of = rewrite_master_weights(
                program, startup, compute_dtype)
            program._amp_master_of = dict(self._master_of)
        if fp8:
            self._fp8_state = wire_fp8_delayed_scaling(
                program, startup, self._amp_lists,
                amax_history_len=self._fp8_amax_history_len)
            if self._fp8_state is not None:
                program._amp_fp8 = self._fp8_state
        if compute_dtype == "float16":
            bop = next((op for op in program.global_block().ops
                        if op.type == "backward"), None)
            if bop is not None and \
                    bop.attrs.get("gradient_merge") is not None:
                import warnings

                warnings.warn(
                    "fp16 loss scaling is not wired under gradient "
                    "merge (the merged-grad cond owns the update "
                    "cadence); training proceeds UNSCALED — expect "
                    "fp16 gradient underflow. Use bfloat16 instead.")
            elif self._use_dynamic_loss_scaling:
                self._scale_state = wire_dynamic_loss_scaling(
                    program, startup, {
                        "init_loss_scaling": self._loss_scaling,
                        "incr_every_n_steps": self._incr_every_n_steps,
                        "decr_every_n_nan_or_inf":
                            self._decr_every_n_nan_or_inf,
                        "incr_ratio": self._incr_ratio,
                        "decr_ratio": self._decr_ratio,
                    })
            elif bop is not None:
                # static scaling: the lowering scales the cotangent and
                # unscales the synced grads — identity math, but fp16
                # backward intermediates stay representable
                bop.attrs["static_loss_scaling"] = self._loss_scaling
        program._version += 1
        return result


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.**15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, amp_dtype="bfloat16",
             amp_level="O2", fp8_amax_history_len=16):
    """Reference: decorator.py:218. `amp_dtype` selects the low-precision
    compute tier: bf16 default (no loss scaling needed), fp16 (dynamic
    loss scaling), or "float8_e4m3" — bf16 carrier compute plus e4m3
    operand / e5m2 gradient quantize-dequantize at the fp8-white-list
    matmul/conv sites, with per-tensor delayed scaling
    (`fp8_amax_history_len`-step abs-max window -> scale) persisted like
    optimizer state. `amp_level` "O1" = cast policy only, "O2"
    (default) = policy + 16-bit live params with ZeRO-sharded fp32
    master weights."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio,
        decr_ratio, amp_dtype=amp_dtype, amp_level=amp_level,
        fp8_amax_history_len=fp8_amax_history_len)
