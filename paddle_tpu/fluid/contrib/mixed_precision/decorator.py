"""AMP decorator (reference:
`python/paddle/fluid/contrib/mixed_precision/decorator.py:27-218`:
OptimizerWithMixedPrecision rewrites the program inserting casts + dynamic
loss scaling via amp_check_finite_and_scale).

TPU-native: bfloat16 shares fp32's exponent range, so no loss scaling is
needed — `decorate()` marks the program with a bf16 compute policy that the
lowering applies per-op (white list ops run on the MXU in bf16; black list
ops compute in fp32; master weights stay fp32 in the Scope). The dynamic
loss-scaling arguments are accepted for API parity and unused unless
use_fp16_guard-style fp16 semantics are explicitly requested.
"""
from __future__ import annotations

from ... import framework
from .fp16_lists import AutoMixedPrecisionLists


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2.**15,
                 use_dynamic_loss_scaling=True, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0,
                 decr_ratio=0.8):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scaling = init_loss_scaling
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def get_loss_scaling(self):
        return self._loss_scaling

    def backward(self, loss, **kwargs):
        return self._optimizer.backward(loss, **kwargs)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        program._amp = True
        program._amp_lists = self._amp_lists
        program._version += 1
        return self._optimizer.minimize(loss, startup_program,
                                        parameter_list, no_grad_set)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.**15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True):
    """Reference: decorator.py:218."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio)
