from .decorator import decorate, OptimizerWithMixedPrecision  # noqa: F401
from .fp16_lists import AutoMixedPrecisionLists  # noqa: F401
from .fp16_utils import (EagerMasterWeightOptimizer,  # noqa: F401
                         master_name, rewrite_master_weights,
                         wire_dynamic_loss_scaling)
