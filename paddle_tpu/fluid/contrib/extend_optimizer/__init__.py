"""contrib.extend_optimizer (reference:
`contrib/extend_optimizer/extend_optimizer_with_weight_decay.py:20-110`):
mix decoupled weight decay into any Optimizer class."""
from __future__ import annotations

from ...framework import default_main_program

__all__ = ["extend_with_decoupled_weight_decay", "DecoupledWeightDecay"]


class DecoupledWeightDecay:
    """Mixin: after the base optimizer's update, subtract
    coeff * lr * param (AdamW-style decay applied to the PARAM, not the
    gradient)."""

    def __init__(self, coeff=0.0, apply_decay_param_fun=None, **kwargs):
        self._coeff = float(coeff)
        self._apply_decay_param_fun = apply_decay_param_fun
        super().__init__(**kwargs)

    def apply_gradients(self, params_grads):
        from ...layers import nn as _nn

        result = super().apply_gradients(params_grads)
        if self._coeff == 0.0:
            return result
        block = default_main_program().global_block()
        # decay scales with the CURRENT lr (schedules included):
        # p <- p - coeff * lr * p, built from the lr graph variable
        from ...layers import tensor as _tensor

        lr_var = self._global_learning_rate()
        for p, g in params_grads:
            if g is None:
                continue
            if self._apply_decay_param_fun is not None and \
                    not self._apply_decay_param_fun(p.name):
                continue
            decay = _tensor.scale(_nn.elementwise_mul(p, lr_var),
                                  scale=self._coeff)
            decayed = _nn.elementwise_sub(p, decay)
            block.append_op(type="assign", inputs={"X": [decayed]},
                            outputs={"Out": [p]}, attrs={})
        return result

    def __str__(self):
        return "DecoupledWeightDecay(coeff=%s) + %s" % (
            self._coeff, super().__str__()
            if hasattr(super(), "__str__") else "")


def extend_with_decoupled_weight_decay(base_optimizer):
    """Returns a subclass of `base_optimizer` whose constructor takes an
    extra `coeff` (weight decay) argument (reference :102)."""

    class OptimizerWithDecoupledWeightDecay(DecoupledWeightDecay,
                                            base_optimizer):
        def __init__(self, weight_decay=0.0, apply_decay_param_fun=None,
                     **kwargs):
            super().__init__(coeff=weight_decay,
                             apply_decay_param_fun=apply_decay_param_fun,
                             **kwargs)

    OptimizerWithDecoupledWeightDecay.__name__ = (
        base_optimizer.__name__ + "WithDecoupledWeightDecay")
    return OptimizerWithDecoupledWeightDecay
