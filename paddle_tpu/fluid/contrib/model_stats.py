"""Model statistics tools.

Reference parity: `python/paddle/fluid/contrib/model_stat.py` (summary
of params/FLOPs per layer) and `contrib/memory_usage_calc.py` (estimate
of a program's memory footprint). TPU note: the real device numbers come
from `core.memory.memory_stats()` (PJRT); these static estimates mirror
the reference's var-size walk for pre-run sizing."""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from .. import framework

_DTYPE_BYTES = {"float32": 4, "float64": 8, "float16": 2, "bfloat16": 2,
                "int64": 8, "int32": 4, "int16": 2, "int8": 1, "uint8": 1,
                "bool": 1}


def summary(program=None, batch_size=1) -> Dict:
    """Per-op param/FLOPs table (reference: model_stat.summary).
    Returns {"total_params", "total_flops", "rows": [...]}. FLOPs are
    counted for the matmul-bearing ops (mul/matmul/conv2d) the way the
    reference does; elementwise work is omitted (XLA fuses it anyway)."""
    program = program or framework.default_main_program()
    block = program.global_block()
    rows = []
    total_params = 0
    total_flops = 0
    for v in block.vars.values():
        if isinstance(v, framework.Parameter):
            n = int(np.prod([d for d in v.shape if d > 0])) \
                if v.shape else 1
            total_params += n
    for op in block.ops:
        flops = _op_flops(block, op, batch_size)
        if flops:
            rows.append((op.type, flops))
            total_flops += flops
    return {"total_params": total_params, "total_flops": total_flops,
            "rows": rows}


def _shape_of(block, name, batch_size=1) -> Tuple[int, ...]:
    v = block._find_var_recursive(name)
    if v is None or not v.shape:
        return ()
    return tuple(int(d) if d > 0 else int(batch_size)
                 for d in v.shape)


def _op_flops(block, op, batch_size):
    t = op.type
    if t in ("mul", "matmul", "matmul_v2"):
        xs = _shape_of(block, op.input_names["X"][0], batch_size)
        ys = _shape_of(block, op.input_names["Y"][0])
        if len(xs) >= 2 and len(ys) >= 2:
            m = int(np.prod(xs[:-1]))
            return 2 * m * xs[-1] * ys[-1]
    if t in ("conv2d", "depthwise_conv2d"):
        out = _shape_of(block, op.output_names["Output"][0],
                        batch_size)
        w = _shape_of(block, op.input_names["Filter"][0])
        if len(out) == 4 and len(w) == 4:
            return 2 * int(np.prod(out)) * w[1] * w[2] * w[3]
    return 0


def memory_usage(program=None, batch_size=1) -> Dict:
    """Static estimate of a program's variable footprint (reference:
    memory_usage_calc.memory_usage). The batch dim (-1) is filled with
    batch_size. Ground-truth check: `reconcile_with_attribution`
    compares this estimate against the compiled truth of
    `Executor.attribution_report` and warns on drift."""
    program = program or framework.default_main_program()
    block = program.global_block()
    persistable = 0
    activations = 0
    for v in block.vars.values():
        if not v.shape:
            continue
        n = int(np.prod([d if d > 0 else batch_size for d in v.shape]))
        nbytes = n * _DTYPE_BYTES.get(str(v.dtype), 4)
        if v.persistable:
            persistable += nbytes
        else:
            activations += nbytes
    return {"persistable_bytes": persistable,
            "activation_bytes": activations,
            "total_bytes": persistable + activations}


def reconcile_with_attribution(attribution_report, program=None,
                               batch_size=1, tol=0.10) -> Dict:
    """Cross-check the STATIC `memory_usage` estimate against the
    COMPILED truth of an `Executor.attribution_report` (the estimate
    previously had no ground-truth check at all). Two classes compare:

    - "persistable": the static persistable-var walk vs the report's
      param + master + opt_state + state_other classes. ZeRO sharding
      and 16-bit AMP params make the compiled side SMALLER by design —
      a large delta here quantifies exactly what sharding saved.
    - "activation": the static non-persistable walk vs the report's
      feed bytes + stamped activation/temp attribution (when present).

    Each class whose relative delta exceeds `tol` (default 10%) emits a
    python warning naming the class and the per-class byte delta.
    Returns {"classes": {name: {static_bytes, compiled_bytes,
    delta_frac, ok}}, "ok": bool, "tol": tol}."""
    import warnings

    static = memory_usage(program, batch_size)
    classes = (attribution_report or {}).get("classes", {})
    compiled_persistable = sum(
        classes.get(k, 0)
        for k in ("param", "master", "opt_state", "state_other"))
    mem = (attribution_report or {}).get("memory", {})
    act = (attribution_report or {}).get("activation", {})
    compiled_activation = classes.get("feed", 0) + min(
        act.get("matched_bytes", 0),
        mem.get("temp_bytes", 0) + mem.get("output_bytes", 0))

    def one(name, static_b, compiled_b):
        denom = max(compiled_b, 1)
        delta = abs(static_b - compiled_b) / float(denom)
        ok = delta <= tol
        if not ok:
            warnings.warn(
                "model_stats.memory_usage drifts %.0f%% from compiled "
                "truth on %r: static %.2f MB vs attributed %.2f MB "
                "(Executor.attribution_report). The static walk knows "
                "nothing of ZeRO sharding, AMP dtypes or XLA buffer "
                "reuse — trust the attribution report for sizing."
                % (100.0 * delta, name, static_b / 1e6,
                   compiled_b / 1e6))
        return {"static_bytes": int(static_b),
                "compiled_bytes": int(compiled_b),
                "delta_frac": round(delta, 4), "ok": ok}

    out = {
        "classes": {
            "persistable": one("persistable",
                               static["persistable_bytes"],
                               compiled_persistable),
            "activation": one("activation",
                              static["activation_bytes"],
                              compiled_activation),
        },
        "tol": tol,
    }
    out["ok"] = all(c["ok"] for c in out["classes"].values())
    return out
