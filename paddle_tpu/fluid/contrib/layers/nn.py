"""contrib layer builders (reference:
`python/paddle/fluid/contrib/layers/nn.py`) — wrappers over the
specialty/text-matching/TDM op family."""
from __future__ import annotations

from ...layer_helper import LayerHelper, apply_op

__all__ = [
    "fused_elemwise_activation", "var_conv_2d", "match_matrix_tensor",
    "sequence_topk_avg_pooling", "tree_conv", "fused_embedding_seq_pool",
    "multiclass_nms2", "search_pyramid_hash", "shuffle_batch",
    "partial_concat", "partial_sum", "tdm_child", "tdm_sampler",
    "rank_attention", "batch_fc",
]


def _one(op, inputs, attrs, slot="Out", dtype=None):
    return apply_op(op, op, inputs, attrs, [slot], out_dtype=dtype)[0]


def _apply_act(out, act):
    """Reference contrib layers run helper.append_activation(out)."""
    if not act:
        return out
    from ...layers import nn as _nn

    return getattr(_nn, act)(out)


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    return _one("fused_elemwise_activation", {"X": [x], "Y": [y]},
                {"functor_list": list(functor_list), "axis": axis,
                 "scale": scale})


def var_conv_2d(input, row, col, input_channel, output_channel,
                filter_size, stride=1, param_attr=None, act=None,
                dtype="float32", name=None):
    """Reference: contrib/layers/nn.py:106 — creates the
    [output_channel, filter_size^2] filter parameter W."""
    from ...initializer import XavierInitializer

    helper = LayerHelper("var_conv_2d")
    w = helper.create_parameter(
        attr=param_attr,
        shape=[output_channel, filter_size * filter_size], dtype=dtype,
        default_initializer=XavierInitializer())
    out = _one("var_conv_2d",
               {"X": [input], "ROW": [row], "COLUMN": [col], "W": [w]},
               {"input_channel": input_channel,
                "output_channel": output_channel,
                "kernel_h": filter_size, "kernel_w": filter_size,
                "stride_h": stride, "stride_w": stride})
    return _apply_act(out, act)


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None, x_lod=None,
                        y_lod=None):
    """Reference: contrib/layers/nn.py:223 — learns W [dim_in,
    channel_num, dim_in]; returns (out, tmp). Padded-representation
    note: ragged batches pass their sequence offsets through the
    x_lod/y_lod vars (the reference carries them as LoD on x/y);
    without them the whole batch is ONE sequence pair."""
    from ...initializer import XavierInitializer

    helper = LayerHelper("match_matrix_tensor")
    dim_in = x.shape[-1]
    w = helper.create_parameter(
        attr=param_attr, shape=[dim_in, channel_num, dim_in],
        dtype=dtype, default_initializer=XavierInitializer())
    ins = {"X": [x], "Y": [y], "W": [w]}
    if x_lod is not None:
        ins["XLod"] = [x_lod]
    if y_lod is not None:
        ins["YLod"] = [y_lod]
    outs = apply_op("match_matrix_tensor", "match_matrix_tensor",
                    ins, {"dim_t": channel_num}, ["Out", "Tmp"])
    return _apply_act(outs[0], act), outs[1]


def sequence_topk_avg_pooling(input, row, col, topks, channel_num,
                              x_lod=None):
    """Reference: contrib/layers/nn.py:310. Padded-representation
    note: the reference's ROW/COLUMN are LoDTensors whose LoD (not
    data) carries the per-pair matrix extents; here `row`/`col` ARE
    the offset vectors ([0, r0, r0+r1, ...]), and x_lod optionally
    carries X's own offsets."""
    ins = {"X": [input], "ROWLod": [row], "COLUMNLod": [col]}
    if x_lod is not None:
        ins["XLod"] = [x_lod]
    outs = apply_op("sequence_topk_avg_pooling",
                    "sequence_topk_avg_pooling", ins,
                    {"topks": list(topks), "channel_num": channel_num},
                    ["Out", "pos"])
    return outs[0]


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """Reference: contrib/layers/nn.py:378 — creates the
    [feature, 3, output_size, num_filters] Filter parameter."""
    from ...initializer import XavierInitializer

    helper = LayerHelper("tree_conv")
    feature = nodes_vector.shape[-1]
    filt = helper.create_parameter(
        attr=param_attr,
        shape=[feature, 3, output_size, num_filters], dtype="float32",
        default_initializer=XavierInitializer())
    return _one("tree_conv",
                {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                 "Filter": [filt]},
                {"output_size": output_size, "num_filters": num_filters,
                 "max_depth": max_depth, "act": act})


def fused_embedding_seq_pool(input, size, is_sparse=False,
                             padding_idx=None, combiner="sum",
                             param_attr=None, dtype="float32"):
    from ...initializer import XavierInitializer

    helper = LayerHelper("fused_embedding_seq_pool")
    w = helper.create_parameter(attr=param_attr, shape=list(size),
                                dtype=dtype,
                                default_initializer=XavierInitializer())
    return _one("fused_embedding_seq_pool", {"Ids": [input], "W": [w]},
                {"combiner": combiner, "is_sparse": is_sparse,
                 "padding_idx": padding_idx
                 if padding_idx is not None else -1})


def multiclass_nms2(*args, **kwargs):
    from ...layers.detection import multiclass_nms2 as _impl

    return _impl(*args, **kwargs)


def search_pyramid_hash(input, num_emb, space_len, pyramid_layer,
                        rand_len, drop_out_percent, is_training,
                        use_filter, white_list_len, black_list_len,
                        seed, lr, param_attr=None, param_attr_wl=None,
                        param_attr_bl=None, name=None,
                        distribute_update_vars=None, dtype="float32"):
    """Reference: contrib/layers/nn.py:645 (op name pyramid_hash) —
    creates the [space_len + rand_len, rand_len] hash embedding W."""
    from ...initializer import XavierInitializer

    helper = LayerHelper("search_pyramid_hash")
    w = helper.create_parameter(
        attr=param_attr, shape=[space_len + rand_len, rand_len],
        dtype=dtype, default_initializer=XavierInitializer())
    return _one("pyramid_hash", {"X": [input], "W": [w]},
                {"num_emb": num_emb, "space_len": space_len,
                 "pyramid_layer": pyramid_layer, "rand_len": rand_len,
                 "drop_out_percent": drop_out_percent,
                 "is_training": is_training, "seed": seed, "lr": lr})


def shuffle_batch(x, seed=None):
    return _one("shuffle_batch", {"X": [x]},
                {"startup_seed": seed if seed is not None else 0})


def partial_concat(input, start_index=0, length=-1):
    ins = input if isinstance(input, (list, tuple)) else [input]
    return _one("partial_concat", {"X": list(ins)},
                {"start_index": start_index, "length": length})


def partial_sum(input, start_index=0, length=-1):
    ins = input if isinstance(input, (list, tuple)) else [input]
    return _one("partial_sum", {"X": list(ins)},
                {"start_index": start_index, "length": length})


def tdm_child(x, node_nums, child_nums, param_attr=None, dtype="int32"):
    """Reference: contrib/layers/nn.py:942 — the tree-info table is a
    (frozen) parameter of shape [node_nums, 3 + child_nums]."""
    helper = LayerHelper("tdm_child")
    tree_info = helper.create_parameter(
        attr=param_attr, shape=[node_nums, 3 + child_nums],
        dtype="int64")
    tree_info.trainable = False
    outs = apply_op("tdm_child", "tdm_child",
                    {"X": [x], "TreeInfo": [tree_info]},
                    {"child_nums": child_nums},
                    ["Child", "LeafMask"])
    return outs[0], outs[1]


def tdm_sampler(x, neg_samples_num_list, layer_node_num_list,
                leaf_node_num, tree_travel_attr=None, tree_layer_attr=None,
                output_positive=True, output_list=True, seed=0,
                tree_dtype="int64", dtype="int64"):
    """Reference: contrib/layers/nn.py:1027 — Travel/Layer tables are
    (frozen) parameters; layer_offset_lod derives from
    layer_node_num_list."""
    helper = LayerHelper("tdm_sampler")
    layer_nums = len(neg_samples_num_list)
    layer_offset = [0]
    for n in layer_node_num_list:
        layer_offset.append(layer_offset[-1] + int(n))
    travel = helper.create_parameter(
        attr=tree_travel_attr, shape=[leaf_node_num, layer_nums],
        dtype="int64")
    travel.trainable = False
    layer = helper.create_parameter(
        attr=tree_layer_attr, shape=[layer_offset[-1], 1], dtype="int64")
    layer.trainable = False
    outs = apply_op("tdm_sampler", "tdm_sampler",
                    {"X": [x], "Travel": [travel], "Layer": [layer]},
                    {"neg_samples_num_list": list(neg_samples_num_list),
                     "layer_offset_lod": layer_offset,
                     "output_positive": output_positive, "seed": seed},
                    ["Out", "Labels", "Mask"])
    return outs[0], outs[1], outs[2]


def rank_attention(input, rank_offset, rank_param_shape,
                   rank_param_attr=None, max_rank=3, max_size=0):
    from ...initializer import XavierInitializer

    helper = LayerHelper("rank_attention")
    rank_param = helper.create_parameter(
        attr=rank_param_attr, shape=rank_param_shape, dtype="float32",
        default_initializer=XavierInitializer())
    return _one("rank_attention",
                {"X": [input], "RankOffset": [rank_offset],
                 "RankParam": [rank_param]},
                {"MaxRank": max_rank, "MaxSize": max_size})


def batch_fc(input, param_size, param_attr, bias_size, bias_attr,
             act=None):
    helper = LayerHelper("batch_fc")
    w = helper.create_parameter(attr=param_attr, shape=list(param_size),
                                dtype="float32")
    b = helper.create_parameter(attr=bias_attr, shape=list(bias_size),
                                dtype="float32")
    out = _one("batch_fc", {"Input": [input], "W": [w], "Bias": [b]}, {})
    from ...layers import nn as _nn

    return getattr(_nn, act)(out) if act else out
