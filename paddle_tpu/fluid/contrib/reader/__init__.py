"""contrib.reader (reference: `contrib/reader/distributed_reader.py`)."""
from .distributed_reader import distributed_batch_reader  # noqa: F401
