"""Distributed batch reader (reference:
`contrib/reader/distributed_reader.py:21`): each trainer keeps every
num_trainers-th batch of the wrapped reader, offset by its trainer id
(env contract PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM)."""
from __future__ import annotations

import os

__all__ = ["distributed_batch_reader"]


def distributed_batch_reader(batch_reader):
    trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    trainers = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    def decorated():
        for idx, batch in enumerate(batch_reader()):
            if idx % trainers == trainer_id:
                yield batch

    return decorated
