"""fluid.contrib (reference: `python/paddle/fluid/contrib/`)."""
from . import mixed_precision  # noqa: F401
from . import layers  # noqa: F401
from . import model_stats  # noqa: F401
from . import model_stats as model_stat  # noqa: F401  (reference name)
from . import op_frequence  # noqa: F401
from .op_frequence import op_freq_statistic  # noqa: F401
from . import slim  # noqa: F401
from . import extend_optimizer  # noqa: F401
from . import reader  # noqa: F401
from . import decoder  # noqa: F401
from . import memory_usage_calc  # noqa: F401
from .memory_usage_calc import memory_usage  # noqa: F401
from .extend_optimizer import (  # noqa: F401
    extend_with_decoupled_weight_decay,
)
