from . import mixed_precision  # noqa: F401
from . import model_stats  # noqa: F401
