"""Op-frequency statistics (reference:
`python/paddle/fluid/contrib/op_frequence.py:23`): per-op-type counts
and adjacent-pair counts over a Program — the profiling aid used to
pick fusion candidates."""
from __future__ import annotations

from collections import OrderedDict

from ..framework import Program


def op_freq_statistic(program):
    """Returns (uni_op_freq, adj_2_op_freq): single-op counts and
    producer->consumer adjacent-pair counts ("a->b"), both sorted by
    frequency descending (reference op_frequence.py:23)."""
    if not isinstance(program, Program):
        raise TypeError("The input type should be Program. "
                        "But you passed in %s" % (type(program),))

    uni_op_freq = OrderedDict()
    adj_2_op_freq = OrderedDict()
    parameters = {p.name for p in program.global_block().all_parameters()}

    var_gen_op = {}
    for op in program.global_block().ops:
        # single-op counts (ops writing only parameters don't count,
        # matching the reference's skip of param-init noise)
        recorded = False
        for var_name in op.output_arg_names:
            if var_name in parameters:
                continue
            if not recorded:
                uni_op_freq[op.type] = uni_op_freq.get(op.type, 0) + 1
                recorded = True
        # adjacent pairs: producer of each non-param input -> this op
        for var_name in op.input_arg_names:
            if var_name in parameters:
                continue
            if var_name in var_gen_op and var_gen_op[var_name]:
                key = "%s->%s" % (var_gen_op[var_name][-1], op.type)
                adj_2_op_freq[key] = adj_2_op_freq.get(key, 0) + 1
        for var_name in op.output_arg_names:
            var_gen_op.setdefault(var_name, []).append(op.type)

    uni = OrderedDict(sorted(uni_op_freq.items(),
                             key=lambda kv: -kv[1]))
    adj = OrderedDict(sorted(adj_2_op_freq.items(),
                             key=lambda kv: -kv[1]))
    return uni, adj
