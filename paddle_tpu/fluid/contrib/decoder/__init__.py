"""contrib.decoder (reference:
`python/paddle/fluid/contrib/decoder/beam_search_decoder.py`)."""
from .beam_search_decoder import (  # noqa: F401
    InitState, StateCell, TrainingDecoder, BeamSearchDecoder,
)
