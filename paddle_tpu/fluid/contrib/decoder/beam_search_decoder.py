"""Pure-python decoder API (reference:
`contrib/decoder/beam_search_decoder.py:35` — InitState, StateCell,
TrainingDecoder, BeamSearchDecoder).

The reference builds these on DynamicRNN over LoD tensors; the
TPU-native build keeps the same four-class API but runs the training
decode as a python loop over padded [B, T, D] steps (unrolled at trace
time, fused by XLA — same approach as layers/rnn_decode.py) and routes
inference beam search through the jit-able `beam_search` op machinery.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ...layer_helper import LayerHelper
from ...layers import nn as nn_layers
from ...layers import tensor as tensor_layers
from ...layers import rnn_decode as _rnn_decode

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]


class InitState:
    """Initial decoder state (reference :43): either an explicit `init`
    var or a zero-filled [batch_size, shape...] created from a boot var."""

    def __init__(self, init=None, shape=None, value=0.0,
                 init_boot=None, need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is not None:
            self._init = tensor_layers.fill_constant_batch_size_like(
                init_boot, [-1] + list(shape or [1]), dtype, value)
        else:
            raise ValueError(
                "InitState needs `init` or `init_boot` (reference "
                "beam_search_decoder.py:70)")
        self._need_reorder = need_reorder

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class StateCell:
    """Computation cell of one decoding step (reference :159): named
    inputs + named states + an @state_updater that maps them to the new
    states."""

    def __init__(self, inputs: Dict, states: Dict[str, InitState],
                 out_state: str, name=None):
        self._inputs = dict(inputs)
        self._init_states = dict(states)
        self._out_state = out_state
        self._cur_states = {k: v.value for k, v in states.items()}
        self._updater: Optional[Callable] = None
        self.name = name

    def get_state(self, state_name):
        return self._cur_states[state_name]

    def get_input(self, input_name):
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        self._cur_states[state_name] = state_value

    def state_updater(self, updater):
        self._updater = updater
        return updater

    def compute_state(self, inputs: Dict):
        self._inputs.update(inputs)
        if self._updater is None:
            raise RuntimeError("StateCell has no @state_updater")
        self._updater(self)

    def update_states(self):
        # the reference commits pending state writes here; writes in
        # this build are immediate, so nothing to flush
        pass

    def out_state(self):
        return self._cur_states[self._out_state]

    def reset(self):
        self._cur_states = {k: v.value
                            for k, v in self._init_states.items()}


class TrainingDecoder:
    """Teacher-forced decoding loop (reference :384): iterate the
    StateCell over the target sequence's time axis and collect step
    outputs into [B, T, D]."""

    def __init__(self, state_cell: StateCell, name=None):
        self._state_cell = state_cell
        self._outputs: List = []
        self._step_inputs: List = []
        self._static_inputs: Dict = {}
        self.name = name

    @property
    def state_cell(self):
        return self._state_cell

    class _Block:
        def __init__(self, decoder):
            self._d = decoder

        def __enter__(self):
            return self._d

        def __exit__(self, *exc):
            return False

    def block(self):
        return TrainingDecoder._Block(self)

    def step_input(self, x):
        """Register the [B, T, D] teacher sequence; returns it for use
        inside the loop body builder."""
        self._step_inputs.append(x)
        return x

    def static_input(self, x):
        self._static_inputs[len(self._static_inputs)] = x
        return x

    def output(self, *outputs):
        self._outputs.extend(outputs)

    def decode(self, seq, step_fn, max_len=None):
        """Run the loop: step_fn(cell, x_t) -> step output [B, D]; the
        outputs stack to [B, T, D]. (The reference drives this through
        DynamicRNN; here the loop unrolls at trace time.)"""
        t = max_len or seq.shape[1]
        outs = []
        self._state_cell.reset()
        for i in range(t):
            x_t = nn_layers.squeeze(
                nn_layers.slice(seq, axes=[1], starts=[i], ends=[i + 1]),
                axes=[1])
            outs.append(step_fn(self._state_cell, x_t))
        stacked = nn_layers.stack(outs, axis=1)
        self._outputs.append(stacked)
        return stacked

    def __call__(self):
        if not self._outputs:
            raise RuntimeError(
                "TrainingDecoder has no outputs; run decode() first")
        return self._outputs[-1] if len(self._outputs) == 1 \
            else self._outputs


class BeamSearchDecoder:
    """Inference beam search (reference :525): wraps the modern
    layers.rnn_decode BeamSearchDecoder/dynamic_decode machinery under
    the contrib constructor signature."""

    def __init__(self, state_cell: StateCell, init_ids, init_scores,
                 target_dict_dim, word_dim, input_var_dict=None,
                 topk_size=50, sparse_emb=True, max_len=100,
                 beam_size=4, end_id=1, name=None):
        self._state_cell = state_cell
        self._target_dict_dim = target_dict_dim
        self._word_dim = word_dim
        self._max_len = max_len
        self._beam_size = beam_size
        self._end_id = end_id
        self._start_id = 0
        self.name = name
        self._emb_name = (name or "contrib_bsd") + "_emb"
        self._fc_name = (name or "contrib_bsd") + "_out_fc"

    def decode(self, cell=None):
        """Run dynamic_decode with a cell adapter over the StateCell's
        updater. Returns (ids, scores)."""
        sc = self._state_cell
        emb_helper = LayerHelper(self._emb_name)
        emb_w = emb_helper.create_parameter(
            None, shape=[self._target_dict_dim, self._word_dim],
            dtype="float32")
        fc_helper = LayerHelper(self._fc_name)
        out_state0 = sc._init_states[sc._out_state].value
        d_model = int(out_state0.shape[-1])
        out_w = fc_helper.create_parameter(
            None, shape=[d_model, self._target_dict_dim],
            dtype="float32")

        input_names = [k for k in sc._inputs]
        if len(input_names) != 1:
            raise ValueError(
                "contrib BeamSearchDecoder needs a StateCell with exactly "
                "one input (got %r); multi-input cells must use "
                "layers.dynamic_decode directly" % (input_names,))
        if len(sc._init_states) != 1:
            raise ValueError(
                "contrib BeamSearchDecoder threads only one state "
                "through the beam (got states %r); multi-state cells "
                "(LSTM h+c) must use layers.dynamic_decode directly"
                % (sorted(sc._init_states),))
        in_name = input_names[0]

        class _CellAdapter(_rnn_decode.RNNCell):
            def call(self, inputs, states):
                sc._cur_states[sc._out_state] = states
                sc.compute_state({in_name: inputs})
                new_state = sc.out_state()
                return new_state, new_state

        decoder = _rnn_decode.BeamSearchDecoder(
            _CellAdapter(), start_token=self._start_id,
            end_token=self._end_id, beam_size=self._beam_size,
            embedding_fn=lambda ids: nn_layers.gather(emb_w, ids),
            output_fn=lambda h: nn_layers.matmul(h, out_w))
        return _rnn_decode.dynamic_decode(
            decoder, inits=out_state0, max_step_num=self._max_len)
