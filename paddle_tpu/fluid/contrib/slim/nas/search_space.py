"""Search-space contract for NAS (reference:
`python/paddle/fluid/contrib/slim/nas/search_space.py`): a space maps a
token vector to a candidate network plus a reward."""
from __future__ import annotations

__all__ = ["SearchSpace"]


class SearchSpace:
    """Subclass and implement the three hooks; `create_net` builds the
    candidate (a program, a Layer, or any trainable object your
    reward_fn understands) from the tokens."""

    def init_tokens(self):
        """Initial token vector."""
        raise NotImplementedError("Abstract method.")

    def range_table(self):
        """list<int>: tokens[i] ranges over [0, range_table[i])."""
        raise NotImplementedError("Abstract method.")

    def create_net(self, tokens):
        """Build the candidate network for `tokens`."""
        raise NotImplementedError("Abstract method.")
