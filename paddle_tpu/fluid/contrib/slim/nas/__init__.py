from .search_space import SearchSpace  # noqa: F401
from .sa_nas import SANAS  # noqa: F401
