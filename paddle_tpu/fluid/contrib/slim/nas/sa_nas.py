"""SANAS: simulated-annealing architecture/compression search
(reference: `python/paddle/fluid/contrib/slim/nas/light_nas_strategy.py`
LightNASStrategy + the controller_server/search_agent socket pair).

TPU-native design: the reference ran a socket ControllerServer so many
GPU workers could pull tokens; here candidate evaluation is one jitted
computation per candidate on the local chip, and multi-host search (if
wanted) rides the existing jax.distributed / host_collectives tier
rather than a bespoke socket protocol — so the search loop itself is a
plain synchronous driver."""
from __future__ import annotations

from ..searcher.controller import SAController

__all__ = ["SANAS"]


class SANAS:
    def __init__(self, search_space, reward_fn, reduce_rate=0.85,
                 init_temperature=10.0, seed=None):
        """search_space: a SearchSpace; reward_fn(net, tokens) -> float
        (higher is better; fold FLOPs/latency penalties in here)."""
        self._space = search_space
        self._reward_fn = reward_fn
        self._controller = SAController(
            reduce_rate=reduce_rate, init_temperature=init_temperature,
            seed=seed)
        self.history = []  # [(tokens, reward)]

    def search(self, max_iterations=20, constrain_func=None):
        """Run the SA loop; returns (best_tokens, best_reward)."""
        tokens = list(self._space.init_tokens())
        self._controller.reset(self._space.range_table(), tokens,
                               constrain_func)
        net = self._space.create_net(tokens)
        reward = float(self._reward_fn(net, tokens))
        self._controller.update(tokens, reward)
        self.history.append((tokens, reward))
        for _ in range(int(max_iterations)):
            tokens = self._controller.next_tokens()
            net = self._space.create_net(tokens)
            reward = float(self._reward_fn(net, tokens))
            self._controller.update(tokens, reward)
            self.history.append((tokens, reward))
        return self._controller.best_tokens, self._controller.max_reward
