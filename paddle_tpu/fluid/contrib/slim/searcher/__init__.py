from .controller import EvolutionaryController, SAController  # noqa: F401
