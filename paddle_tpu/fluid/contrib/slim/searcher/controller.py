"""Evolutionary search controllers (reference:
`python/paddle/fluid/contrib/slim/searcher/controller.py` —
EvolutionaryController ABC + SAController simulated annealing). The
controller is pure host-side python; the candidate programs it scores
run as ordinary jitted computations, so nothing here touches the device
path."""
from __future__ import annotations

import math

import numpy as np

__all__ = ["EvolutionaryController", "SAController"]


class EvolutionaryController:
    """Abstract controller for evolutionary searching methods."""

    def update(self, tokens, reward):
        raise NotImplementedError("Abstract method.")

    def reset(self, range_table, init_tokens, constrain_func=None):
        raise NotImplementedError("Abstract method.")

    def next_tokens(self):
        raise NotImplementedError("Abstract method.")


class SAController(EvolutionaryController):
    """Simulated-annealing controller (reference: controller.py:58).
    tokens[i] ranges over [0, range_table[i]); a worse candidate is
    accepted with prob exp((reward - best)/T), T decaying by
    reduce_rate per iteration."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=None):
        self._range_table = range_table
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._reward = -np.inf  # -inf, not -1: rewards may be negative
        self._tokens = None
        self._constrain_func = None
        self._max_reward = -np.inf
        self._best_tokens = None
        self._iter = 0
        self._rng = np.random.RandomState(seed)

    @property
    def max_reward(self):
        return self._max_reward

    @property
    def best_tokens(self):
        return self._best_tokens

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens)
        self._iter = 0
        # a reused controller must not carry the previous search's
        # acceptance state or best
        self._reward = -np.inf
        self._max_reward = -np.inf
        self._best_tokens = None

    def update(self, tokens, reward):
        self._iter += 1
        temperature = (self._init_temperature
                       * self._reduce_rate ** self._iter)
        if (reward > self._reward
                or self._rng.random_sample()
                <= math.exp(min((reward - self._reward) / temperature,
                                50.0))):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self):
        """Mutate one random position of the current tokens; retry until
        the constraint (if any) accepts the candidate."""
        for _ in range(1000):
            tokens = list(self._tokens)
            pos = int(self._rng.randint(len(tokens)))
            tokens[pos] = int(self._rng.randint(self._range_table[pos]))
            if self._constrain_func is None or self._constrain_func(
                    tokens):
                return tokens
        raise RuntimeError(
            "SAController: constrain_func rejected 1000 candidates")
