"""slim.distillation — knowledge distillation losses (reference:
`python/paddle/fluid/contrib/slim/distillation/distiller.py`)."""
from .distiller import (  # noqa: F401
    L2Distiller, FSPDistiller, SoftLabelDistiller, merge_teacher,
)
