"""Distillers (reference: `contrib/slim/distillation/distiller.py` —
L2Distiller:25, FSPDistiller:103, SoftLabelDistiller:195). The
reference's GraphWrapper merge step becomes `merge_teacher`, which
clones the teacher program's ops/params into the student program under
a name prefix so the combined loss lowers to ONE XLA computation (the
teacher forward is jitted together with the student step and fused by
the compiler — no separate executor pass)."""
from __future__ import annotations

from .... import framework
from ....layer_helper import apply_op
from ....layers import tensor as _tensor
from ....layers import nn as _nn
from ....layers import loss as _loss

TEACHER_PREFIX = "teacher_"


def merge_teacher(teacher_program, student_program=None,
                  prefix=TEACHER_PREFIX, scope=None, teacher_scope=None):
    """Clone teacher ops+vars into the student program with prefixed
    names (feeds keep their names so both nets read the same batch).
    Teacher params are copied into the scope under the prefixed name and
    marked stop_gradient. Returns {orig_name: merged_name}."""
    import jax.numpy as jnp
    from ....framework import default_main_program
    from .....core.scope import global_scope

    student_program = student_program or default_main_program()
    scope = scope or global_scope()
    teacher_scope = teacher_scope or scope
    block = student_program.global_block()
    t_block = teacher_program.global_block()

    name_map = {}
    for vname, var in t_block.vars.items():
        if var.is_data:
            name_map[vname] = vname       # shared feeds
            continue
        new_name = prefix + vname
        name_map[vname] = new_name
        if new_name not in block.vars:
            nv = block.create_var(
                name=new_name, shape=var.shape, dtype=var.dtype,
                persistable=var.persistable)
            nv.stop_gradient = True
        tv = teacher_scope.find_var(vname)
        if tv is not None and var.persistable:
            scope.set_var(new_name, jnp.asarray(tv))
    for op in t_block.ops:
        block.append_op(
            type=op.type,
            inputs={slot: [name_map.get(n, n) for n in names]
                    for slot, names in op.input_names.items()},
            outputs={slot: [name_map.get(n, n) for n in names]
                     for slot, names in op.output_names.items()},
            attrs=dict(op.attrs))
    return name_map


class L2Distiller:
    """L2 loss between a student and a teacher feature (reference
    distiller.py:25)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.weight = distillation_loss_weight

    def distiller_loss(self, program=None):
        program = program or framework.default_main_program()
        block = program.global_block()
        s = block.vars[self.student_feature_map]
        t = block.vars[self.teacher_feature_map]
        diff = _nn.elementwise_sub(s, t)
        loss = _nn.reduce_mean(
            apply_op("square", "square", {"X": [diff]}, {}, ["Out"],
                     out_dtype=s.dtype)[0])
        return _tensor.scale(loss, scale=self.weight)


class FSPDistiller:
    """Flow-of-solution-procedure distillation (reference
    distiller.py:103): L2 between student and teacher FSP matrices of
    (section-start, section-end) feature-map pairs."""

    def __init__(self, student_pairs, teacher_pairs,
                 distillation_loss_weight=1.0):
        self.student_pairs = list(student_pairs)
        self.teacher_pairs = list(teacher_pairs)
        self.weight = distillation_loss_weight

    def _fsp(self, block, a_name, b_name):
        a, b = block.vars[a_name], block.vars[b_name]
        return apply_op("fsp", "fsp", {"X": [a], "Y": [b]}, {}, ["Out"],
                        out_dtype=a.dtype)[0]

    def distiller_loss(self, program=None):
        program = program or framework.default_main_program()
        block = program.global_block()
        losses = []
        for (sa, sb), (ta, tb) in zip(self.student_pairs,
                                      self.teacher_pairs):
            sm = self._fsp(block, sa, sb)
            tm = self._fsp(block, ta, tb)
            diff = _nn.elementwise_sub(sm, tm)
            losses.append(_nn.reduce_mean(
                apply_op("square", "square", {"X": [diff]}, {}, ["Out"],
                         out_dtype="float32")[0]))
        total = losses[0]
        for l2 in losses[1:]:
            total = _nn.elementwise_add(total, l2)
        return _tensor.scale(total, scale=self.weight)


class SoftLabelDistiller:
    """Soft cross entropy between temperature-scaled teacher and student
    logits (reference distiller.py:195)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 student_temperature=1.0, teacher_temperature=1.0,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.student_temperature = student_temperature
        self.teacher_temperature = teacher_temperature
        self.weight = distillation_loss_weight

    def distiller_loss(self, program=None):
        program = program or framework.default_main_program()
        block = program.global_block()
        s = block.vars[self.student_feature_map]
        t = block.vars[self.teacher_feature_map]
        s_scaled = _tensor.scale(s, scale=1.0 / self.student_temperature)
        t_scaled = _tensor.scale(t, scale=1.0 / self.teacher_temperature)
        t_soft = _nn.softmax(t_scaled)
        ce = _loss.softmax_with_cross_entropy(s_scaled, t_soft,
                                              soft_label=True)
        return _tensor.scale(_nn.reduce_mean(ce), scale=self.weight)
