"""fluid.contrib.slim — model compression (reference:
`python/paddle/fluid/contrib/slim/`). Quantization (QAT + PTQ) is
implemented; pruning/NAS/distillation are descoped per SURVEY.md §7.9."""
from . import quantization  # noqa: F401
