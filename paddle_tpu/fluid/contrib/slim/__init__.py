"""fluid.contrib.slim — model compression (reference:
`python/paddle/fluid/contrib/slim/`): quantization (QAT + PTQ),
magnitude/structure pruning, distillation losses, and NAS (SAController
simulated-annealing searcher + SANAS loop over a SearchSpace)."""
from . import quantization  # noqa: F401
from . import prune  # noqa: F401
from . import distillation  # noqa: F401
from . import searcher  # noqa: F401
from . import nas  # noqa: F401
