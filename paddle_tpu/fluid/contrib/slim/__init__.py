"""fluid.contrib.slim — model compression (reference:
`python/paddle/fluid/contrib/slim/`): quantization (QAT + PTQ),
magnitude/structure pruning, and distillation losses. NAS/searcher are
descoped per SURVEY.md §7.9."""
from . import quantization  # noqa: F401
from . import prune  # noqa: F401
from . import distillation  # noqa: F401
