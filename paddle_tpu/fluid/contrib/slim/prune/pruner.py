"""Pruners (reference: `contrib/slim/prune/pruner.py:22-107` Pruner /
StructurePruner; the strategy machinery of `prune_strategy.py` is
reduced to the two entry points real users call — prune a program's
params by ratio, and measure per-param sensitivity).

TPU-native design: pruning is masking. XLA has no sparse kernels worth
targeting for unstructured sparsity, so `MagnitudePruner` zeroes weights
(keeping shapes static = no recompile), while `StructurePruner` computes
the kept-index sets that a rebuild-with-smaller-shapes flow (the
reference's conv-channel pruning) consumes.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class Pruner:
    """Base class of all pruners (reference pruner.py:22)."""

    def prune(self, param):
        raise NotImplementedError


class StructurePruner(Pruner):
    """Group pruning along an axis (reference pruner.py:34): computes
    which indices survive by group-criterion ranking ('l1_norm')."""

    def __init__(self, pruning_axis: Dict[str, int],
                 criterions: Dict[str, str]):
        self.pruning_axis = dict(pruning_axis)
        self.criterions = dict(criterions)

    def _axis_for(self, name):
        return self.pruning_axis.get(name, self.pruning_axis.get("*", 0))

    def _criterion_for(self, name):
        return self.criterions.get(name, self.criterions.get("*",
                                                             "l1_norm"))

    def cal_pruned_idx(self, name, param, ratio, axis=None):
        """Indices to REMOVE along `axis` by ascending criterion."""
        param = np.asarray(param)
        axis = self._axis_for(name) if axis is None else axis
        crit = self._criterion_for(name)
        if crit != "l1_norm":
            raise ValueError("unsupported criterion %r" % crit)
        reduce_axes = tuple(i for i in range(param.ndim) if i != axis)
        scores = np.abs(param).sum(axis=reduce_axes)
        n_prune = int(param.shape[axis] * ratio)
        return np.argsort(scores)[:n_prune].tolist()

    def prune_tensor(self, tensor, pruned_idx, pruned_axis, lazy=False):
        """Remove (or with lazy=True zero) the given indices."""
        tensor = np.asarray(tensor)
        if lazy:
            out = tensor.copy()
            sl = [slice(None)] * tensor.ndim
            sl[pruned_axis] = pruned_idx
            out[tuple(sl)] = 0.0
            return out
        keep = [i for i in range(tensor.shape[pruned_axis])
                if i not in set(pruned_idx)]
        return np.take(tensor, keep, axis=pruned_axis)


class MagnitudePruner(Pruner):
    """Unstructured magnitude pruning: zero the smallest |w| entries
    (shape-preserving, so compiled executables stay valid)."""

    def __init__(self, ratio: float):
        self.ratio = float(ratio)

    def prune(self, param):
        param = np.asarray(param)
        k = int(param.size * self.ratio)
        if k <= 0:
            return param.copy()
        thresh = np.partition(np.abs(param).ravel(), k - 1)[k - 1]
        out = param.copy()
        out[np.abs(out) <= thresh] = 0.0
        return out


def prune_program(program, scope, ratios: Dict[str, float],
                  place=None, lazy=True,
                  pruner: Optional[Pruner] = None):
    """Prune named parameters of a program in-scope (reference
    prune_strategy.py applies StructurePruner over the graph; here the
    scope tensors are rewritten directly). ratios: param name -> ratio
    ('*' applies to every parameter). Returns {name: sparsity}."""
    import jax.numpy as jnp

    all_params = {p.name: p for p in program.all_parameters()}
    targets = {}
    for name, ratio in ratios.items():
        if name == "*":
            for p in all_params:
                targets.setdefault(p, ratio)
        else:
            targets[name] = ratio
    result = {}
    for name, ratio in targets.items():
        var = scope.find_var(name)
        if var is None:
            continue
        # never mutate a caller-supplied pruner; per-param magnitude
        # pruning gets a fresh instance at this param's ratio
        impl = pruner if pruner is not None else MagnitudePruner(ratio)
        if isinstance(impl, MagnitudePruner):
            impl = MagnitudePruner(ratio)
            new = impl.prune(var)
        else:
            idx = impl.cal_pruned_idx(name, np.asarray(var), ratio)
            new = impl.prune_tensor(var, idx, impl._axis_for(name),
                                    lazy=lazy)
        scope.set_var(name, jnp.asarray(new))
        result[name] = 1.0 - (np.count_nonzero(new) / new.size)
    return result


def sensitivity(program, scope, param_names, eval_fn, ratios=(0.1, 0.3,
                                                             0.5, 0.7)):
    """Per-parameter pruning sensitivity (reference
    auto_prune_strategy.py): prune one param at each ratio, run eval_fn()
    -> metric, restore; returns {param: {ratio: metric}}."""
    import jax.numpy as jnp

    out = {}
    for name in param_names:
        var = scope.find_var(name)
        if var is None:
            continue
        orig = np.asarray(var).copy()
        out[name] = {}
        for ratio in ratios:
            scope.set_var(name, jnp.asarray(
                MagnitudePruner(ratio).prune(orig)))
            out[name][ratio] = float(eval_fn())
        scope.set_var(name, jnp.asarray(orig))
    return out
