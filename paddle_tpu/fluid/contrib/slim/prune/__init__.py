"""slim.prune — magnitude/structure pruning (reference:
`python/paddle/fluid/contrib/slim/prune/pruner.py` +
`prune_strategy.py`)."""
from .pruner import (  # noqa: F401
    Pruner, StructurePruner, MagnitudePruner, prune_program,
    sensitivity,
)
