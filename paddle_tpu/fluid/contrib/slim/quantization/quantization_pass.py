"""QAT program rewrite.

Reference parity: `contrib/slim/quantization/quantization_pass.py` —
QuantizationTransformPass inserts fake_quantize/dequantize ops on the
weights and activations of quantizable ops (conv2d, mul, matmul, ...);
QuantizationFreezePass converts a trained QAT program for int8 inference.
TPU-native: the fake-quant ops carry straight-through gradients for free
(ops/quant_ops.py), and the whole QAT step still lowers to ONE jitted XLA
computation — no separate quant kernels to schedule.
"""
from __future__ import annotations

from typing import List, Optional

from ....framework import Operator
from .... import framework


_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul",
                "matmul_v2")
_WEIGHT_SLOTS = {"conv2d": "Filter", "depthwise_conv2d": "Filter",
                 "mul": "Y", "matmul": "Y", "matmul_v2": "Y"}
_INPUT_SLOTS = {"conv2d": "Input", "depthwise_conv2d": "Input",
                "mul": "X", "matmul": "X", "matmul_v2": "X"}
# channel axis of the weight tensor (conv filters are [oc, ic, kh, kw];
# mul/matmul weights are [in, out] — per-OUT-channel is axis 1). This
# goes beyond the reference, whose per-channel path covers only 4-D
# conv filters (always dim 0, no quant_axis attr in this version):
# per-out-channel quantization of mul/matmul weights is an extension.
# Custom `quantizable_op_type` entries outside this table default to
# axis 0 via `.get(op.type, 0)`.
_W_QUANT_AXIS = {"conv2d": 0, "depthwise_conv2d": 0, "mul": 1,
                 "matmul": 1, "matmul_v2": 1}
# ops whose output scale equals their input scale: OutScaleForInference
# propagates out_threshold through them (reference: freeze-pass scale
# propagation over the _op_real_in_out_name identity list)
_SCALE_INVARIANT = ("relu", "relu6", "reshape", "reshape2", "transpose",
                    "transpose2", "flatten", "flatten2", "squeeze",
                    "squeeze2", "unsqueeze", "unsqueeze2", "pool2d")


class QuantizationTransformPass:
    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="abs_max",
                 quantizable_op_type=_QUANTIZABLE, moving_rate=0.9,
                 skip_pattern="skip_quant"):
        self._wbits = weight_bits
        self._abits = activation_bits
        self._act_type = activation_quantize_type
        self._w_type = weight_quantize_type
        self._ops = tuple(quantizable_op_type)
        self._rate = moving_rate
        self._skip = skip_pattern

    def apply(self, program, startup_program=None):
        """Insert fake quant/dequant before each quantizable op's weight
        and activation inputs. Returns the (mutated) program."""
        startup = startup_program or framework.default_startup_program()
        block = program.global_block()
        new_ops: List[Operator] = []
        quantized_acts = {}
        for op in list(block.ops):
            if op.type in self._ops and not op.attrs.get(self._skip) \
                    and not op.attrs.get("skip_quant"):
                # custom quantizable_op_type outside the builtin five:
                # default to the generic X (activation) / Y (weight)
                # slots and per-channel axis 0
                for slot, maker in (
                        (_INPUT_SLOTS.get(op.type, "X"),
                         self._quant_act),
                        (_WEIGHT_SLOTS.get(op.type, "Y"),
                         self._quant_weight)):
                    names = op.input_names.get(slot)
                    if not names:
                        continue
                    src = names[0]
                    v = block._find_var_recursive(src)
                    if v is None or str(v.dtype) not in (
                            "float32", "float16", "bfloat16"):
                        continue
                    key = (src, maker is self._quant_weight)
                    if key not in quantized_acts:
                        if maker is self._quant_weight:
                            quantized_acts[key] = maker(
                                block, startup, src, v, new_ops,
                                quant_axis=_W_QUANT_AXIS.get(
                                    op.type, 0))
                        else:
                            quantized_acts[key] = maker(
                                block, startup, src, v, new_ops)
                    op.input_names[slot] = [quantized_acts[key]]
            new_ops.append(op)
        block.ops[:] = new_ops
        program._version += 1
        return program

    def _quant_weight(self, block, startup, src, v, new_ops,
                      quant_axis=0):
        out = block.create_var(name=src + ".quantized",
                               shape=v.shape, dtype=v.dtype,
                               stop_gradient=False)
        channel_wise = self._w_type == "channel_wise_abs_max"
        # per-channel: one scale per slice along quant_axis (the
        # reference's per-channel conv weight quantization in the
        # TRANSFORM, not just at freeze)
        scale_shape = ([int(v.shape[quant_axis])] if channel_wise
                       else [1])
        scale = block.create_var(name=src + ".quant_scale",
                                 shape=scale_shape, dtype="float32",
                                 stop_gradient=True)
        op_type = ("fake_channel_wise_quantize_abs_max" if channel_wise
                   else "fake_quantize_abs_max")
        attrs = {"bit_length": self._wbits}
        if channel_wise:
            attrs["quant_axis"] = quant_axis
        new_ops.append(Operator(
            block, op_type, inputs={"X": [src]},
            outputs={"Out": [out.name], "OutScale": [scale.name]},
            attrs=attrs))
        return out.name

    def _quant_act(self, block, startup, src, v, new_ops):
        out = block.create_var(name=src + ".quantized",
                               shape=v.shape, dtype=v.dtype,
                               stop_gradient=False)
        if self._act_type == "moving_average_abs_max":
            state = block.create_var(name=src + ".quant_state",
                                     shape=[1], dtype="float32",
                                     persistable=True,
                                     stop_gradient=True)
            sblock = startup.global_block()
            sblock.create_var(name=state.name, shape=[1],
                              dtype="float32", persistable=True)
            sblock.append_op(type="fill_constant", inputs={},
                             outputs={"Out": [state.name]},
                             attrs={"shape": [1], "dtype": "float32",
                                    "value": 0.0})
            new_ops.append(Operator(
                block, "fake_quantize_moving_average_abs_max",
                inputs={"X": [src], "InScale": [state.name]},
                outputs={"Out": [out.name], "OutScale": [state.name]},
                attrs={"bit_length": self._abits,
                       "moving_rate": self._rate}))
        else:
            # persistable OutScale: the executor then writes each
            # batch's scale back to scope, so FreezePass can bake the
            # last calibrated value in as static_scale (a dead
            # non-persistable OutScale never reaches scope)
            scale = block.create_var(name=src + ".quant_scale",
                                     shape=[1], dtype="float32",
                                     persistable=True,
                                     stop_gradient=True)
            sblock = startup.global_block()
            sblock.create_var(name=scale.name, shape=[1],
                              dtype="float32", persistable=True)
            sblock.append_op(type="fill_constant", inputs={},
                             outputs={"Out": [scale.name]},
                             attrs={"shape": [1], "dtype": "float32",
                                    "value": 0.0})
            new_ops.append(Operator(
                block, "fake_quantize_abs_max", inputs={"X": [src]},
                outputs={"Out": [out.name], "OutScale": [scale.name]},
                attrs={"bit_length": self._abits}))
        return out.name


class OutScaleForTrainingPass:
    """Track the moving-average abs-max of every quantizable op's
    output activation in a persistable state var (reference:
    OutScaleForTrainingPass — it feeds out_threshold at inference).
    The tracker op's OutScale writes a persistable var, so lowering
    keeps it as block state; the passthrough Out is left dangling."""

    _TRACKED = _QUANTIZABLE + ("relu", "pool2d", "elementwise_add",
                               "batch_norm", "softmax")

    def __init__(self, scope=None, place=None, moving_rate=0.9):
        self._rate = moving_rate

    @staticmethod
    def _state_name(act):
        return act + ".out_scale"

    def apply(self, program, startup_program=None):
        startup = startup_program or framework.default_startup_program()
        block = program.global_block()
        sblock = startup.global_block()
        new_ops: List[Operator] = []
        for op in list(block.ops):
            new_ops.append(op)
            if op.type not in self._TRACKED:
                continue
            out_slot = {"batch_norm": "Y", "conv2d": "Output",
                        "depthwise_conv2d": "Output"}.get(op.type, "Out")
            names = op.output_names.get(out_slot)
            if not names:
                continue
            act = names[0]
            v = block._find_var_recursive(act)
            if v is None or str(v.dtype) != "float32":
                continue
            state = self._state_name(act)
            if block._find_var_recursive(state) is not None:
                continue
            sv = block.create_var(name=state, shape=[1],
                                  dtype="float32", persistable=True)
            sv.stop_gradient = True
            sblock.create_var(name=state, shape=[1], dtype="float32",
                              persistable=True)
            sblock.append_op(type="fill_constant", inputs={},
                             outputs={"Out": [state]},
                             attrs={"shape": [1], "dtype": "float32",
                                    "value": 0.0})
            passthrough = block.create_var(name=act + ".scale_obs",
                                           shape=v.shape,
                                           dtype=v.dtype)
            new_ops.append(Operator(
                block, "moving_average_abs_max_scale",
                inputs={"X": [act], "InScale": [state]},
                outputs={"Out": [passthrough.name],
                         "OutScale": [state]},
                attrs={"moving_rate": self._rate}))
        block.ops[:] = new_ops
        program._version += 1
        return program


class OutScaleForInferencePass:
    """Write the tracked output scales onto the producing ops as the
    `out_threshold` attr (reference: OutScaleForInferencePass), then
    propagate through scale-invariant ops (relu/reshape/transpose/
    max-pool...) so every tensor on the quantized path carries a
    threshold. Drops the tracker ops."""

    def __init__(self, scope=None):
        self._scope = scope

    def apply(self, program, scope=None):
        import numpy as np

        scope = scope or self._scope
        if scope is None:
            # proceeding would drop every tracker op while writing zero
            # thresholds — calibration silently destroyed
            raise ValueError(
                "OutScaleForInferencePass needs the scope holding the "
                "trained .out_scale state (pass scope= to __init__ or "
                "apply)")
        block = program.global_block()
        thresholds = {}  # act name -> float scale
        kept: List[Operator] = []
        for op in block.ops:
            if op.type == "moving_average_abs_max_scale":
                state = op.output_names["OutScale"][0]
                v = scope.find_var(state) if scope is not None else None
                if v is not None:
                    s = float(np.asarray(v).reshape(-1)[0])
                    if s > 0:
                        thresholds[op.input_names["X"][0]] = s
                continue  # tracker consumed; drop it
            kept.append(op)
        for op in kept:
            for names in op.output_names.values():
                for n in names:
                    if n in thresholds:
                        op.attrs["out_threshold"] = thresholds[n]
            if op.type in _SCALE_INVARIANT \
                    and "out_threshold" not in op.attrs:
                # scale-invariant: inherit the input's threshold
                for names in op.input_names.values():
                    for n in names:
                        if n in thresholds:
                            op.attrs["out_threshold"] = thresholds[n]
                            for onames in op.output_names.values():
                                for o in onames:
                                    thresholds.setdefault(
                                        o, thresholds[n])
                            break
                    if "out_threshold" in op.attrs:
                        break
        block.ops[:] = kept
        program._version += 1
        return program


class QuantizationFreezePass:
    """Reference: QuantizationFreezePass
    (`contrib/slim/quantization/quantization_pass.py:700`) — after QAT,
    convert the program for int8 inference: weights are snapped to the
    int8 grid IN SCOPE (int8-simulated fp32 values — XLA has no int8
    matmul path worth hand-scheduling), the weight fake-quant ops are
    removed (consumers rewired to the original param, which now holds
    quantized values), per-channel scales land on the consumer op as
    `weight_quant_scale`, and activation quantizers freeze to their
    learned static scales (is_test=True)."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, weight_quantize_type="abs_max"):
        self._scope = scope
        self._wbits = weight_bits
        self._abits = activation_bits
        self._w_type = weight_quantize_type

    def apply(self, program, scope=None):
        import jax.numpy as jnp
        import numpy as np

        scope = scope or self._scope
        block = program.global_block()
        bnt = (1 << (self._wbits - 1)) - 1
        # pass 1: collect weight fake-quant ops (X persistable)
        weight_q = {}  # quantized-name -> (src, op, axis)
        kept: List[Operator] = []
        for op in block.ops:
            if op.type in ("fake_quantize_abs_max",
                           "fake_channel_wise_quantize_abs_max"):
                src = op.input_names["X"][0]
                v = block._find_var_recursive(src)
                if v is not None and getattr(v, "persistable", False) \
                        and scope is not None \
                        and scope.find_var(src) is not None:
                    weight_q[op.output_names["Out"][0]] = (
                        src, op, op.attrs.get("quant_axis", 0))
                    continue  # op removed: weights pre-quantized below
            kept.append(op)

        # pass 2: snap weights to the int8 grid in scope; rewire
        for qname, (src, qop, axis) in weight_q.items():
            w = np.asarray(scope.find_var(src))
            if qop.type == "fake_channel_wise_quantize_abs_max":
                red = tuple(i for i in range(w.ndim) if i != axis)
                scale = np.max(np.abs(w), axis=red, keepdims=True)
            else:
                scale = np.asarray(np.max(np.abs(w))).reshape(
                    tuple(1 for _ in w.shape))
            s = np.maximum(scale, 1e-8)
            wq = np.clip(np.round(w / s * bnt), -bnt, bnt) * s / bnt
            scope.set_var(src, jnp.asarray(wq.astype(w.dtype)))
            for op in kept:
                for slot, names in op.input_names.items():
                    if qname in names:
                        op.input_names[slot] = [
                            src if n == qname else n for n in names]
                        op.attrs["quantization_type"] = (
                            "qat_with_weight_quantize")
                        op.attrs["quant_weight_bits"] = self._wbits
                        op.attrs["weight_quant_scale"] = [
                            float(x) for x in
                            np.asarray(scale).reshape(-1)]

        # pass 3: freeze activation quantizers to their learned scales.
        # moving_average/range variants honor is_test (fixed InScale);
        # plain abs_max has no state input and recomputes per batch —
        # bake the last calibrated OutScale from scope in as the static
        # scale, or inference would silently keep dynamic scales.
        for op in kept:
            if not op.type.startswith("fake_quantize"):
                continue
            op.attrs["is_test"] = True
            if op.type in ("fake_quantize_abs_max",
                           "fake_quantize_dequantize_abs_max") \
                    and scope is not None \
                    and "static_scale" not in op.attrs:
                sv = scope.find_var(op.output_names["OutScale"][0])
                if sv is not None:
                    s = float(np.asarray(sv).reshape(-1)[0])
                    if s > 0:
                        op.attrs["static_scale"] = s
        block.ops[:] = kept
        program._version += 1
        return program
