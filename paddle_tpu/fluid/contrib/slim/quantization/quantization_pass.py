"""QAT program rewrite.

Reference parity: `contrib/slim/quantization/quantization_pass.py` —
QuantizationTransformPass inserts fake_quantize/dequantize ops on the
weights and activations of quantizable ops (conv2d, mul, matmul, ...);
QuantizationFreezePass converts a trained QAT program for int8 inference.
TPU-native: the fake-quant ops carry straight-through gradients for free
(ops/quant_ops.py), and the whole QAT step still lowers to ONE jitted XLA
computation — no separate quant kernels to schedule.
"""
from __future__ import annotations

from typing import List, Optional

from ....framework import Operator
from .... import framework


_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul",
                "matmul_v2")
_WEIGHT_SLOTS = {"conv2d": "Filter", "depthwise_conv2d": "Filter",
                 "mul": "Y", "matmul": "Y", "matmul_v2": "Y"}
_INPUT_SLOTS = {"conv2d": "Input", "depthwise_conv2d": "Input",
                "mul": "X", "matmul": "X", "matmul_v2": "X"}


class QuantizationTransformPass:
    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="abs_max",
                 quantizable_op_type=_QUANTIZABLE, moving_rate=0.9,
                 skip_pattern="skip_quant"):
        self._wbits = weight_bits
        self._abits = activation_bits
        self._act_type = activation_quantize_type
        self._w_type = weight_quantize_type
        self._ops = tuple(quantizable_op_type)
        self._rate = moving_rate
        self._skip = skip_pattern

    def apply(self, program, startup_program=None):
        """Insert fake quant/dequant before each quantizable op's weight
        and activation inputs. Returns the (mutated) program."""
        startup = startup_program or framework.default_startup_program()
        block = program.global_block()
        new_ops: List[Operator] = []
        quantized_acts = {}
        for op in list(block.ops):
            if op.type in self._ops and not op.attrs.get(self._skip) \
                    and not op.attrs.get("skip_quant"):
                for slot, maker in (
                        (_INPUT_SLOTS[op.type], self._quant_act),
                        (_WEIGHT_SLOTS[op.type], self._quant_weight)):
                    names = op.input_names.get(slot)
                    if not names:
                        continue
                    src = names[0]
                    v = block._find_var_recursive(src)
                    if v is None or str(v.dtype) not in (
                            "float32", "float16", "bfloat16"):
                        continue
                    key = (src, maker is self._quant_weight)
                    if key not in quantized_acts:
                        quantized_acts[key] = maker(
                            block, startup, src, v, new_ops)
                    op.input_names[slot] = [quantized_acts[key]]
            new_ops.append(op)
        block.ops[:] = new_ops
        program._version += 1
        return program

    def _quant_weight(self, block, startup, src, v, new_ops):
        out = block.create_var(name=src + ".quantized",
                               shape=v.shape, dtype=v.dtype,
                               stop_gradient=False)
        scale = block.create_var(name=src + ".quant_scale", shape=[1],
                                 dtype="float32", stop_gradient=True)
        op_type = ("fake_channel_wise_quantize_abs_max"
                   if self._w_type == "channel_wise_abs_max"
                   else "fake_quantize_abs_max")
        new_ops.append(Operator(
            block, op_type, inputs={"X": [src]},
            outputs={"Out": [out.name], "OutScale": [scale.name]},
            attrs={"bit_length": self._wbits}))
        return out.name

    def _quant_act(self, block, startup, src, v, new_ops):
        out = block.create_var(name=src + ".quantized",
                               shape=v.shape, dtype=v.dtype,
                               stop_gradient=False)
        if self._act_type == "moving_average_abs_max":
            state = block.create_var(name=src + ".quant_state",
                                     shape=[1], dtype="float32",
                                     persistable=True,
                                     stop_gradient=True)
            sblock = startup.global_block()
            sblock.create_var(name=state.name, shape=[1],
                              dtype="float32", persistable=True)
            sblock.append_op(type="fill_constant", inputs={},
                             outputs={"Out": [state.name]},
                             attrs={"shape": [1], "dtype": "float32",
                                    "value": 0.0})
            new_ops.append(Operator(
                block, "fake_quantize_moving_average_abs_max",
                inputs={"X": [src], "InScale": [state.name]},
                outputs={"Out": [out.name], "OutScale": [state.name]},
                attrs={"bit_length": self._abits,
                       "moving_rate": self._rate}))
        else:
            scale = block.create_var(name=src + ".quant_scale",
                                     shape=[1], dtype="float32",
                                     stop_gradient=True)
            new_ops.append(Operator(
                block, "fake_quantize_abs_max", inputs={"X": [src]},
                outputs={"Out": [out.name], "OutScale": [scale.name]},
                attrs={"bit_length": self._abits}))
        return out.name


class QuantizationFreezePass:
    """Reference: QuantizationFreezePass — after QAT, bake the learned
    scales in as attrs for inference. TPU-native: XLA has no int8 matmul
    path worth hand-scheduling here, so freezing keeps the qdq ops with
    is_test=True (fixed scales); the numerics match int8 deployment."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, weight_quantize_type="abs_max"):
        pass

    def apply(self, program):
        for op in program.global_block().ops:
            if op.type.startswith("fake_quantize"):
                op.attrs["is_test"] = True
        program._version += 1
        return program
