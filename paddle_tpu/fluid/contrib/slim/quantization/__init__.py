from .quantization_pass import (  # noqa: F401
    QuantizationTransformPass, QuantizationFreezePass,
)
from .post_training_quantization import (  # noqa: F401
    PostTrainingQuantization,
)
