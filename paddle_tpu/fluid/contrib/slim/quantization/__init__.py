from .quantization_pass import (  # noqa: F401
    QuantizationTransformPass, QuantizationFreezePass,
    OutScaleForTrainingPass, OutScaleForInferencePass,
)
from .post_training_quantization import (  # noqa: F401
    PostTrainingQuantization,
)
