"""Post-training quantization.

Reference parity: `contrib/slim/quantization/post_training_quantization.py`
— run calibration batches through the fp32 program collecting per-tensor
abs-max statistics, then emit a quantized program whose fake-quant ops
carry the calibrated static scales.
"""
from __future__ import annotations

import numpy as np

from .... import framework
from .quantization_pass import (_INPUT_SLOTS, _WEIGHT_SLOTS,
                                _QUANTIZABLE, QuantizationTransformPass)


class PostTrainingQuantization:
    def __init__(self, executor, program, feed_list, fetch_list,
                 sample_generator=None, batch_nums=10, scope=None,
                 algo="abs_max", quantizable_op_type=_QUANTIZABLE,
                 weight_bits=8, activation_bits=8):
        self._exe = executor
        self._program = program
        self._feed_list = feed_list
        self._fetch_list = fetch_list
        self._samples = sample_generator
        self._batch_nums = batch_nums
        self._scope = scope
        self._algo = algo
        self._ops = tuple(quantizable_op_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        self.scales = {}

    def quantize(self):
        """Calibrate then rewrite. Returns the quantized program."""
        block = self._program.global_block()
        # tensors to calibrate: activation inputs of quantizable ops
        act_names = []
        for op in block.ops:
            if op.type in self._ops:
                names = op.input_names.get(_INPUT_SLOTS[op.type])
                if names and names[0] not in act_names:
                    act_names.append(names[0])

        for i, feed in enumerate(self._samples() if callable(
                self._samples) else self._samples):
            if i >= self._batch_nums:
                break
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=act_names,
                                 scope=self._scope)
            for name, val in zip(act_names, outs):
                cur = float(np.max(np.abs(np.asarray(val))))
                self.scales[name] = max(self.scales.get(name, 0.0), cur)

        # rewrite with static scales: abs_max quant ops see is_test-style
        # fixed scale via a wrapping pass, calibrated scales recorded on
        # the program for save_quantized_model
        pass_ = QuantizationTransformPass(
            weight_bits=self._wbits, activation_bits=self._abits,
            activation_quantize_type="abs_max")
        pass_.apply(self._program)
        # bind the calibrated static scales into the activation quant
        # ops (weights keep dynamic abs-max — they are constants at
        # inference so the two coincide)
        for op in self._program.global_block().ops:
            if op.type == "fake_quantize_abs_max":
                src = op.input_names["X"][0]
                if src in self.scales:
                    op.attrs["static_scale"] = float(self.scales[src])
        self._program._version += 1
        self._program._ptq_scales = dict(self.scales)
        return self._program

    def save_quantized_model(self, save_model_path, model_filename=None,
                             params_filename=None):
        from ..... import fluid

        exe = self._exe
        feed_vars = list(self._feed_list)
        from ....io import save_inference_model

        return save_inference_model(
            save_model_path, feed_vars, self._fetch_list, exe,
            main_program=self._program)
