"""Estimate a program's activation+parameter memory (reference:
`contrib/memory_usage_calc.py:46` memory_usage(program, batch_size) →
(lower MB, upper MB); the reference sums var bytes with a fixed
uncertainty band — same contract here)."""
from __future__ import annotations

import numpy as np

from ..framework import Program

_DTYPE_BYTES = {
    "float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
    "int8": 1, "uint8": 1, "int16": 2, "int32": 4, "int64": 8,
    "bool": 1,
}


def memory_usage(program, batch_size):
    """Rough [lower, upper] MB estimate of the program's tensors with
    dynamic (-1) dims filled by batch_size."""
    if not isinstance(program, Program):
        raise TypeError("memory_usage expects a Program, got %r"
                        % type(program))
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    total = 0.0
    for block in program.blocks:
        for var in block.vars.values():
            shape = [batch_size if (d is None or d < 0) else d
                     for d in (var.shape or [])]
            nbytes = _DTYPE_BYTES.get(str(var.dtype), 4)
            total += float(np.prod(shape)) * nbytes if shape else nbytes
    mb = total / (1024.0 * 1024.0)
    # the reference reports a +-30% band (it cannot see XLA's buffer
    # reuse; neither can we)
    return mb * 0.7, mb * 1.3
