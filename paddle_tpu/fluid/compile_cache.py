"""Persistent, cross-process compilation cache for the Executor.

BENCH_r02 measured 94.7s of XLA compile for one BERT-base step, and the
elastic restart path (PR 9) made restarts *routine*: every transition
re-paid full compilation across the whole cohort. This module is the
persistent tier layered UNDER the Executor's in-memory LRU
(`Executor._cache`):

- the XLA executables themselves persist through
  `jax.experimental.compilation_cache` (`_configure_jax`), rooted at
  `FLAGS_tpu_compile_cache_dir` — the launch supervisor exports the
  same directory to every worker and across restarts, so a restarted
  N' cohort deserializes executables in seconds instead of recompiling;
- a *fingerprint index* (`index/<fp>.json` sentinels) keyed on
  (canonicalized lowered StableHLO, mesh topology, the
  lowering-relevant `FLAGS_tpu_*` set, jax/jaxlib version + backend)
  classifies every fresh-process compile as a persistent *hit* or
  *miss* at the framework's own key granularity — the telemetry the
  raw jax tier cannot provide — and remembers the original compile
  cost so `saved_ms` is bookkeeping, not a guess;
- jax's monitoring hooks (`install_listeners`) attribute the actual
  backend-compile seconds of the first dispatch into the step record's
  `compile_ms` phase and count XLA-level persistent hits, feeding the
  per-compile `compile_cache` telemetry events, the registry
  counters/gauges, the bench `compile_cache` block
  (observability/publish.py) and `tools/perf_analysis.py
  --compile-cache`.

Everything here is inert while `FLAGS_tpu_compile_cache_dir` is unset:
`enabled()` is False, no jax config is touched, no listeners install,
and the Executor's behavior is byte-identical to a cache-less build.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from typing import Dict, Optional

__all__ = ["cache_dir", "enabled", "donation_safe", "ensure", "disable",
           "lowering_flags", "fingerprint", "index_lookup",
           "index_store", "install_listeners", "jax_stats",
           "stats_delta", "record_event", "stats",
           "classified_compile"]

#: flags whose value shapes the lowered computation — part of the
#: fingerprint, so flipping any of them can never alias a stale
#: executable (the StableHLO usually changes too; this is the explicit
#: contract, and it also covers flags whose effect is
#: backend-option-only)
LOWERING_FLAGS = (
    "FLAGS_tpu_donate_buffers",
    "FLAGS_tpu_donate_feed_buffers",
    "FLAGS_tpu_sharded_weight_update",
    "FLAGS_tpu_comm_bucket_mb",
    "FLAGS_tpu_dcn_replicas",
    "FLAGS_tpu_amp_level",
    "FLAGS_tpu_op_provenance",
    "FLAGS_prng_impl",
    "FLAGS_flash_attention_min_seq",
)

_lock = threading.RLock()
_configured_dir: Optional[str] = None
_listeners_installed = False
#: cumulative jax-tier stats fed by the monitoring listeners; snapshot
#: with jax_stats() / delta with stats_delta() around a compile
_jax = {"backend_compiles": 0, "backend_compile_s": 0.0,
        "persistent_hits": 0, "saved_s": 0.0, "retrieval_s": 0.0}
#: process-level roll-up at the framework key granularity (one entry
#: per classified fresh compile; in-memory LRU hits never reach here)
_stats = {"hits": 0, "misses": 0, "compile_ms_total": 0.0,
          "saved_ms_total": 0.0, "warmups": 0}


def cache_dir() -> Optional[str]:
    """The persistent tier's root (FLAGS_tpu_compile_cache_dir), or
    None when the tier is off."""
    from ..utils.flags import get_flag

    d = str(get_flag("FLAGS_tpu_compile_cache_dir", "") or "")
    return d or None


def enabled() -> bool:
    return cache_dir() is not None


def donation_safe() -> bool:
    """XLA:CPU intermittently mis-executes input/output-ALIASED
    (donated) executables DESERIALIZED from the persistent cache
    (jaxlib 0.4.37): the fetch outputs come back correct while the
    aliased state outputs are garbage/NaN — race-shaped, reproduced by
    running tests/compile_cache_runner.py's crash+resume pair in a
    loop, all the way to segfaults, on a stock jax env-var cache with
    no framework code in the loop. With the tier enabled on the CPU
    backend the executor therefore compiles WITHOUT donation
    (lowering.compile_block consults this) — correctness over
    in-place buffer reuse; CPU runs are tests/dev, where HBM pressure
    is moot. On TPU — the production target, whose serialized-
    executable path is the mature one — donation stays on. Returns
    True when donation may be used."""
    if not enabled():
        return True
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 - no backend yet: be conservative
        return False


def ensure() -> Optional[str]:
    """Idempotently wire the persistent tier: point
    jax.experimental.compilation_cache at the flag directory (min
    compile time / entry size floors dropped so EVERY executor
    executable persists — a 40ms test program and a 90s BERT step both
    must round-trip) and install the monitoring listeners. Returns the
    active directory, or None when the flag is unset. Never raises —
    an unwritable directory degrades to cache-off, it must not take
    down a training step."""
    global _configured_dir
    d = cache_dir()
    if d is None:
        return None
    with _lock:
        if _configured_dir == d:
            return d
        try:
            os.makedirs(os.path.join(d, "index"), exist_ok=True)
            _configure_jax(d)
            _configured_dir = d
        except Exception:  # noqa: BLE001 - cache is an optimization
            return None
    install_listeners()
    return d


def _configure_jax(d: str) -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", d)
    for knob, val in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except Exception:  # noqa: BLE001 - older jax: keep defaults
            pass
    _reset_jax_cache_instance()


def _reset_jax_cache_instance() -> None:
    """jax memoizes its cache object at first use — a dir change
    mid-process (tests; a launcher re-pointing the flag) must drop the
    memo or writes keep landing in the OLD directory."""
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _jcc)

        _jcc.reset_cache()
    except Exception:  # noqa: BLE001 - cache not yet initialized
        pass


def disable() -> None:
    """Detach the jax-level tier (tests; the listeners stay — they are
    cheap and delta-snapshotted)."""
    global _configured_dir
    with _lock:
        if _configured_dir is None:
            return
        _configured_dir = None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:  # noqa: BLE001
        pass
    _reset_jax_cache_instance()


# -- jax monitoring listeners ---------------------------------------------

def install_listeners() -> bool:
    """Register (once) for the jax monitoring events that carry the
    ground truth no wrapper can fake: `backend_compile_duration` (the
    actual XLA compile seconds the first dispatch pays — re-attributed
    from the step's dispatch phase into compile_ms),
    `compilation_cache/cache_hits` (the persistent tier served an
    executable) and `compile_time_saved_sec`."""
    global _listeners_installed
    with _lock:
        if _listeners_installed:
            return True
        try:
            import jax._src.monitoring as mon

            # the callbacks fire ON THE COMPILING THREAD: bump the
            # process totals (bench block) AND the caller thread's own
            # tally (jax_stats/stats_delta) — a background warmup
            # thread's compiles must never leak into the main thread's
            # hit/miss verdict or compile_ms re-attribution
            def _on_event(name, **kw):
                if name == "/jax/compilation_cache/cache_hits":
                    with _lock:
                        _jax["persistent_hits"] += 1
                    _thread_jax()["persistent_hits"] += 1

            def _on_duration(name, dur, **kw):
                if name == "/jax/core/compile/backend_compile_duration":
                    with _lock:
                        _jax["backend_compiles"] += 1
                        _jax["backend_compile_s"] += float(dur)
                    tl = _thread_jax()
                    tl["backend_compiles"] += 1
                    tl["backend_compile_s"] += float(dur)
                elif name == "/jax/compilation_cache/" \
                             "compile_time_saved_sec":
                    with _lock:
                        _jax["saved_s"] += max(0.0, float(dur))
                    _thread_jax()["saved_s"] += max(0.0, float(dur))
                elif name == "/jax/compilation_cache/" \
                             "cache_retrieval_time_sec":
                    with _lock:
                        _jax["retrieval_s"] += float(dur)
                    _thread_jax()["retrieval_s"] += float(dur)

            mon.register_event_listener(_on_event)
            mon.register_event_duration_secs_listener(_on_duration)
            _listeners_installed = True
            return True
        except Exception:  # noqa: BLE001 - exotic jax: stats stay 0
            return False


_tls = threading.local()


def _thread_jax() -> Dict[str, float]:
    d = getattr(_tls, "jax", None)
    if d is None:
        d = _tls.jax = {"backend_compiles": 0,
                        "backend_compile_s": 0.0,
                        "persistent_hits": 0, "saved_s": 0.0,
                        "retrieval_s": 0.0}
    return d


def jax_stats() -> Dict[str, float]:
    """THIS thread's cumulative jax-tier tally (snapshot before a
    compile, stats_delta after): thread-local so a concurrent
    background warmup's compiles never pollute the main step loop's
    classification. The process-wide totals live in stats()["jax"]."""
    return dict(_thread_jax())


def stats_delta(before: Dict[str, float]) -> Dict[str, float]:
    now = jax_stats()
    return {k: now[k] - before.get(k, 0) for k in now}


# -- fingerprinting --------------------------------------------------------

_LOC_RE = re.compile(r"\s*loc\([^)]*\)")
_LOCDEF_RE = re.compile(r"^#loc.*$", re.M)


def canonicalize_stablehlo(text: str) -> str:
    """Strip MLIR location metadata (file paths / line numbers of the
    framework source) so the fingerprint survives a repo relocation and
    interpreter-version drift in debug info, while every semantic
    change (an op, a shape, a sharding, a provenance-visible rewrite)
    still changes it."""
    return _LOCDEF_RE.sub("", _LOC_RE.sub("", text))


def mesh_signature(mesh) -> str:
    """Deterministic topology signature: axis names x sizes + the
    device kinds/ids — two processes agree iff they would compile for
    the same device assignment."""
    if mesh is None:
        return "mesh:none"
    try:
        axes = ",".join("%s=%d" % (a, int(mesh.shape[a]))
                        for a in mesh.axis_names)
        devs = ",".join(
            "%s:%s" % (getattr(d, "platform", "?"), getattr(d, "id", "?"))
            for d in mesh.devices.flat)
        return "mesh:(%s)[%s]" % (axes, devs)
    except Exception:  # noqa: BLE001 - exotic mesh object
        return "mesh:%r" % (mesh,)


def lowering_flags() -> Dict[str, object]:
    from ..utils.flags import get_flag

    return {name: get_flag(name) for name in LOWERING_FLAGS}


def fingerprint(stablehlo_text: str, mesh=None, extra=None) -> str:
    """The persistent cache key: sha256 over (canonical StableHLO,
    mesh topology, lowering-relevant flag values, jax/jaxlib version +
    backend platform)."""
    import jax
    import jaxlib

    h = hashlib.sha256()
    h.update(canonicalize_stablehlo(stablehlo_text).encode())
    h.update(mesh_signature(mesh).encode())
    h.update(json.dumps(lowering_flags(), sort_keys=True,
                        default=repr).encode())
    h.update(("jax=%s;jaxlib=%s;backend=%s"
              % (jax.__version__, jaxlib.__version__,
                 jax.default_backend())).encode())
    if extra:
        h.update(json.dumps(extra, sort_keys=True,
                            default=repr).encode())
    return h.hexdigest()


# -- fingerprint index (hit/miss classification + saved-seconds) ----------

def _index_path(fp: str) -> Optional[str]:
    d = cache_dir()
    if d is None:
        return None
    return os.path.join(d, "index", fp + ".json")


def index_lookup(fp: str) -> Optional[dict]:
    """The sentinel a previous process (or an evicted-and-readmitted
    entry in THIS process) left after compiling this fingerprint —
    presence means the XLA executables for it are already on disk."""
    path = _index_path(fp)
    if path is None or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def index_store(fp: str, meta: dict) -> Optional[str]:
    """Atomically record a completed compile (tmp-then-replace: the
    whole cohort shares one index and a torn sentinel must never
    poison a reader)."""
    path = _index_path(fp)
    if path is None:
        return None
    doc = dict(meta)
    doc.setdefault("fingerprint", fp)
    doc.setdefault("created_ts", time.time())
    doc.setdefault("flags", lowering_flags())
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True, default=repr)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def new_entry_bytes(since_ts: float) -> int:
    """Approximate bytes the jax tier wrote since `since_ts` — the
    on-disk cost of a miss (compiles are rare enough that one
    directory scan per miss is noise). APPROXIMATE by design: the
    cache dir is shared across a cohort, so ranks cold-starting
    simultaneously each count the window's overlapping writes; treat
    the per-event `bytes` field as disk-cost magnitude, not an exact
    per-module size (the miss sentinel pins whatever this rank
    observed)."""
    d = cache_dir()
    if d is None:
        return 0
    total = 0
    try:
        with os.scandir(d) as it:
            for e in it:
                try:
                    st = e.stat()
                except OSError:
                    continue
                if e.is_file() and st.st_mtime >= since_ts - 1.0:
                    total += int(st.st_size)
    except OSError:
        return 0
    return total


def classified_compile(lowered, mesh=None, extra=None, source="aot"):
    """Compile a `jax.stages.Lowered` while classifying it against the
    persistent tier — the generic twin of the Executor's per-entry
    classification, used by non-Program compile paths (the serving
    engine's decode/prefill step buckets, `source="serving_decode"` /
    `"serving_prefill"`; `tools/perf_analysis.py --compile-cache`
    breaks its report down by this source tag).

    Returns (compiled, info) where info is None when the tier is off,
    else {"status": "hit"|"miss", "fingerprint", "compile_ms",
    "saved_ms"}. The jax-stat delta is THREAD-LOCAL (jax_stats), so
    concurrent warmups classify independently. Classification errors
    degrade to an unclassified compile — never a failed one."""
    ensure()
    if not enabled():
        return lowered.compile(), None
    try:
        fp = fingerprint(lowered.as_text(), mesh, extra=extra)
        prev = index_lookup(fp)
    except Exception:  # noqa: BLE001 - classification is telemetry
        return lowered.compile(), None
    before, t0 = jax_stats(), time.time()
    compiled = lowered.compile()
    d = stats_delta(before)
    comp_ms = max(0.0, d["backend_compile_s"]) * 1e3
    hit = prev is not None or d["persistent_hits"] > 0
    saved_ms = max(0.0, d["saved_s"] * 1e3)
    nbytes = 0
    if prev is not None:
        saved_ms = max(saved_ms,
                       float(prev.get("compile_ms", 0.0)) - comp_ms)
        nbytes = int(prev.get("bytes", 0))
    elif not hit:
        nbytes = new_entry_bytes(t0)
    status = "hit" if hit else "miss"
    record_event(status, fp, compile_ms=comp_ms, saved_ms=saved_ms,
                 nbytes=nbytes, source=source)
    if prev is None:
        index_store(fp, {"compile_ms": round(comp_ms, 3),
                         "bytes": nbytes, "source": str(source),
                         "mesh": mesh_signature(mesh)})
    return compiled, {"status": status, "fingerprint": fp,
                      "compile_ms": round(comp_ms, 3),
                      "saved_ms": round(saved_ms, 3)}


# -- telemetry -------------------------------------------------------------

def record_event(status: str, fp: Optional[str], compile_ms: float,
                 saved_ms: float = 0.0, nbytes: int = 0,
                 source: str = "step") -> Optional[dict]:
    """One classified compile -> a `compile_cache` telemetry event
    (JSONL sink + flight ring), the registry counters/gauges the bench
    block assembles from, and the module roll-up. Never raises."""
    with _lock:
        if status == "hit":
            _stats["hits"] += 1
        elif status == "miss":
            _stats["misses"] += 1
        if source == "warmup":
            _stats["warmups"] += 1
        _stats["compile_ms_total"] += max(0.0, float(compile_ms))
        _stats["saved_ms_total"] += max(0.0, float(saved_ms))
    try:
        from ..observability import registry

        reg = registry()
        reg.inc("compile_cache." + status)
        reg.set_gauge("compile_cache.compile_ms_total",
                      round(_stats["compile_ms_total"], 3))
        reg.set_gauge("compile_cache.saved_ms_total",
                      round(_stats["saved_ms_total"], 3))
        return reg.event(
            "compile_cache", status=str(status),
            key=(fp or "")[:16], compile_ms=round(float(compile_ms), 3),
            saved_ms=round(float(saved_ms), 3), bytes=int(nbytes),
            source=str(source))
    except Exception:  # noqa: BLE001 - telemetry must never kill a step
        return None


def stats() -> dict:
    """Process roll-up + on-disk tier inventory — the bench
    `compile_cache` block's payload."""
    with _lock:
        out = dict(_stats)
        out["jax"] = dict(_jax)
    d = cache_dir()
    out["enabled"] = d is not None
    out["dir"] = d
    total = out["hits"] + out["misses"]
    out["hit_rate"] = (out["hits"] / total) if total else None
    out["persistent_entries"] = 0
    out["persistent_bytes"] = 0
    out["index_entries"] = 0
    if d and os.path.isdir(d):
        try:
            with os.scandir(d) as it:
                for e in it:
                    if e.is_file():
                        out["persistent_entries"] += 1
                        try:
                            out["persistent_bytes"] += int(
                                e.stat().st_size)
                        except OSError:
                            pass
            idx = os.path.join(d, "index")
            if os.path.isdir(idx):
                out["index_entries"] = len(
                    [f for f in os.listdir(idx)
                     if f.endswith(".json")])
        except OSError:
            pass
    return out


def _reset_for_tests() -> None:
    global _configured_dir
    with _lock:
        _configured_dir = None
        for k in _jax:
            _jax[k] = 0 if isinstance(_jax[k], int) else 0.0
        for k in _stats:
            _stats[k] = 0 if isinstance(_stats[k], int) else 0.0
    _tls.jax = None
