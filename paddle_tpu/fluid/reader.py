"""Data pipeline (reference: `python/paddle/fluid/reader.py:113-954` —
DataLoader.from_generator feeding a C++ blocking queue, multiprocess
dataloader in dataloader/).

TPU-native: the bottleneck to hide is host->HBM transfer; DataLoader
prefetches batches on a background thread and (optionally) device_puts
ahead of consumption — the analogue of the double-buffered
`operators/reader/buffered_reader.cc`.
"""
from __future__ import annotations

import queue as _queue
import threading
from typing import Callable, List, Optional

import numpy as np


class DataLoaderBase:
    def __iter__(self):
        raise NotImplementedError


class _GeneratorLoader(DataLoaderBase):
    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False, drop_last=True):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._return_list = return_list
        self._batch_reader = None
        self._places = None
        self._use_double_buffer = use_double_buffer

    # -- wiring ------------------------------------------------------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batched():
            batch = []
            for sample in reader():
                batch.append(sample if isinstance(sample, (list, tuple))
                             else (sample,))
                if len(batch) == batch_size:
                    yield [np.stack([b[i] for b in batch])
                           for i in range(len(batch[0]))]
                    batch = []
            if batch and not drop_last:
                yield [np.stack([b[i] for b in batch])
                       for i in range(len(batch[0]))]

        self._batch_reader = batched
        self._places = places
        return self

    def set_sample_list_generator(self, reader, places=None):
        def batched():
            for samples in reader():
                n = len(samples[0])
                yield [np.stack([np.asarray(s[i]) for s in samples])
                       for i in range(n)]

        self._batch_reader = batched
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        self._batch_reader = reader
        self._places = places
        return self

    # -- iteration ---------------------------------------------------------
    def __iter__(self):
        if self._batch_reader is None:
            raise RuntimeError("DataLoader: no generator set")
        q: _queue.Queue = _queue.Queue(maxsize=self._capacity)
        stop = object()

        def produce():
            try:
                for batch in self._batch_reader():
                    q.put(batch)
            finally:
                q.put(stop)

        t = threading.Thread(target=produce, daemon=True)
        t.start()

        feed_names = [getattr(v, "name", v) for v in self._feed_list]
        while True:
            item = q.get()
            if item is stop:
                break
            if isinstance(item, dict):
                yield item
            elif feed_names and not self._return_list:
                yield dict(zip(feed_names, item))
            else:
                yield item

    def start(self):
        pass

    def reset(self):
        pass


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        return _GeneratorLoader(feed_list, capacity, use_double_buffer,
                                iterable, return_list, drop_last)

    @staticmethod
    def from_dataset(dataset, places, drop_last=True):
        raise NotImplementedError("dataset loader: use train_from_dataset")

    def __init__(self, dataset=None, feed_list=None, places=None,
                 return_list=False, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, timeout=0,
                 worker_init_fn=None):
        # map-style dataset loader (2.0 API)
        self._dataset = dataset
        self._batch_size = batch_size
        self._shuffle = shuffle
        self._drop_last = drop_last
        self._return_list = return_list
        self._feed_list = feed_list or []
        self._collate = collate_fn

    def __iter__(self):
        n = len(self._dataset)
        idx = np.arange(n)
        if self._shuffle:
            np.random.shuffle(idx)
        batches = []
        for i in range(0, n, self._batch_size):
            sel = idx[i:i + self._batch_size]
            if len(sel) < self._batch_size and self._drop_last:
                continue
            batches.append(sel)
        for sel in batches:
            samples = [self._dataset[int(j)] for j in sel]
            if self._collate:
                yield self._collate(samples)
                continue
            first = samples[0]
            if isinstance(first, (list, tuple)):
                yield [np.stack([np.asarray(s[i]) for s in samples])
                       for i in range(len(first))]
            else:
                yield np.stack([np.asarray(s) for s in samples])

    def __len__(self):
        n = len(self._dataset)
        if self._drop_last:
            return n // self._batch_size
        return (n + self._batch_size - 1) // self._batch_size


class PyReader(_GeneratorLoader):
    """Legacy PyReader API (reference: reader.py PyReader)."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(feed_list, capacity, use_double_buffer, iterable,
                         return_list)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)
