"""Data pipeline (reference: `python/paddle/fluid/reader.py:113-954` —
DataLoader.from_generator feeding a C++ blocking queue; multiprocess
dataloader in `fluid/dataloader/dataloader_iter.py`).

TPU-native: the bottleneck to hide is host->HBM transfer; DataLoader
prefetches batches through the C++ native blocking channel
(paddle_tpu.core.native.NativeChannel — the analogue of the reference's
lod_tensor_blocking_queue) on a background thread, and map-style loading
fans out to multiprocess workers like the reference's _DataLoaderIter.
With `use_double_buffer` and an accelerator place, the double buffer now
extends past the host channel into HBM: a second stage
(reader/prefetcher.py) issues non-blocking `jax.device_put`s
`FLAGS_tpu_prefetch_depth` batches ahead, so the consuming step finds
its feeds already on device (reference analogue:
`operators/reader/buffered_reader.cc`'s device-side copy stream).
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as _queue
import threading
from typing import Callable, List, Optional

import numpy as np


class _ReaderError:
    """Wraps an exception raised in the producer thread so the consumer
    re-raises it instead of seeing a silently truncated epoch."""

    def __init__(self, exc):
        self.exc = exc


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, (list, tuple)):
        return [np.stack([np.asarray(s[i]) for s in samples])
                for i in range(len(first))]
    return np.stack([np.asarray(s) for s in samples])


class DataLoaderBase:
    def __iter__(self):
        raise NotImplementedError


class _PrefetchQueue:
    """Bounded blocking handoff between the producer thread and the
    consumer. Same-process, so items pass by reference through a python
    queue — the C++ NativeChannel is reserved for paths that cross a
    language/process boundary (the native MultiSlotDataFeed uses it
    internally), where its byte-buffer semantics pay for themselves."""

    def __init__(self, capacity: int):
        self._q = _queue.Queue(maxsize=capacity)
        self._stop = object()

    def push(self, item):
        self._q.put(item)

    def close(self):
        self._q.put(self._stop)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._stop:
                return
            yield item


class _GeneratorLoader(DataLoaderBase):
    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False, drop_last=True):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._return_list = return_list
        self._batch_reader = None
        self._places = None
        self._use_double_buffer = use_double_buffer

    # -- wiring ------------------------------------------------------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batched():
            batch = []
            for sample in reader():
                batch.append(sample if isinstance(sample, (list, tuple))
                             else (sample,))
                if len(batch) == batch_size:
                    yield [np.stack([b[i] for b in batch])
                           for i in range(len(batch[0]))]
                    batch = []
            if batch and not drop_last:
                yield [np.stack([b[i] for b in batch])
                       for i in range(len(batch[0]))]

        self._batch_reader = batched
        self._places = places
        return self

    def set_sample_list_generator(self, reader, places=None):
        def batched():
            for samples in reader():
                n = len(samples[0])
                yield [np.stack([np.asarray(s[i]) for s in samples])
                       for i in range(n)]

        self._batch_reader = batched
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        self._batch_reader = reader
        self._places = places
        return self

    # -- iteration ---------------------------------------------------------
    def _device_buffered(self):
        """True when the host double buffer should extend to HBM: the
        loader targets an accelerator place (host numpy stays the
        contract for CPU places — dygraph consumers expect it)."""
        if not self._use_double_buffer:
            return False
        places = self._places
        if places is None:
            return False
        from ..core.place import CUDAPlace, TPUPlace

        seq = places if isinstance(places, (list, tuple)) else [places]
        return any(isinstance(p, (TPUPlace, CUDAPlace)) for p in seq)

    def _host_iter(self):
        q = _PrefetchQueue(self._capacity)

        def produce():
            try:
                for batch in self._batch_reader():
                    q.push(batch)
            except BaseException as e:  # surface reader errors downstream
                q.push(_ReaderError(e))
            finally:
                q.close()

        t = threading.Thread(target=produce, daemon=True)
        t.start()

        feed_names = [getattr(v, "name", v) for v in self._feed_list]
        for item in q:
            if isinstance(item, _ReaderError):
                raise RuntimeError(
                    "DataLoader generator raised") from item.exc
            if isinstance(item, dict):
                yield item
            elif feed_names and not self._return_list:
                yield dict(zip(feed_names, item))
            else:
                yield item

    def __iter__(self):
        if self._batch_reader is None:
            raise RuntimeError("DataLoader: no generator set")
        if not self._device_buffered():
            yield from self._host_iter()
            return
        from ..reader.prefetcher import prefetch_to_device

        pf = prefetch_to_device(self._host_iter())
        try:
            yield from pf
        finally:
            pf.close()  # early break drains in-flight device buffers

    def start(self):
        pass

    def reset(self):
        pass


def _worker_loop(dataset, collate_fn, index_queue, result_queue,
                 worker_init_fn, worker_id):
    """Runs in a child process: pull index batches, push collated arrays
    (reference: dataloader/dataloader_iter.py _worker_loop)."""
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    collate = collate_fn or _default_collate
    while True:
        job = index_queue.get()
        if job is None:
            break
        batch_idx, indices = job
        try:
            samples = [dataset[int(i)] for i in indices]
            result_queue.put((batch_idx, collate(samples), None))
        except Exception as e:  # surface worker errors to the parent
            result_queue.put((batch_idx, None, repr(e)))
    result_queue.put((None, worker_id, None))  # worker-done marker


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        return _GeneratorLoader(feed_list, capacity, use_double_buffer,
                                iterable, return_list, drop_last)

    @staticmethod
    def from_dataset(dataset, places, drop_last=True):
        raise NotImplementedError("dataset loader: use train_from_dataset")

    def __init__(self, dataset=None, feed_list=None, places=None,
                 return_list=False, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, timeout=0,
                 worker_init_fn=None):
        # map-style dataset loader (2.0 API)
        self._dataset = dataset
        self._batch_size = batch_size
        self._batch_sampler = batch_sampler
        self._shuffle = shuffle
        self._drop_last = drop_last
        self._return_list = return_list
        self._feed_list = feed_list or []
        self._collate = collate_fn
        self._num_workers = max(0, int(num_workers))
        self._timeout = timeout
        self._worker_init_fn = worker_init_fn
        self._places = places
        self._use_buffer_reader = use_buffer_reader

    def _batches(self):
        if self._batch_sampler is not None:
            yield from self._batch_sampler
            return
        n = len(self._dataset)
        idx = np.arange(n)
        if self._shuffle:
            np.random.shuffle(idx)
        for i in range(0, n, self._batch_size):
            sel = idx[i:i + self._batch_size]
            if len(sel) < self._batch_size and self._drop_last:
                continue
            yield sel

    def _device_buffered(self):
        """Map-style analogue of _GeneratorLoader._device_buffered: with
        `use_buffer_reader` (the default) and an accelerator place, the
        buffer reader extends past host numpy into HBM — batches arrive
        as pre-put jax arrays (reader/prefetcher.py issues the async
        device_puts) and the dygraph train loops consume them without a
        host round-trip (hapi _as_variables / to_variable pass device
        arrays through)."""
        if not self._use_buffer_reader:
            return False
        places = self._places
        if places is None:
            return False
        from ..core.place import CUDAPlace, TPUPlace

        seq = places if isinstance(places, (list, tuple)) else [places]
        return any(isinstance(p, (TPUPlace, CUDAPlace)) for p in seq)

    def _iter_host(self):
        if self._num_workers == 0:
            collate = self._collate or _default_collate
            for sel in self._batches():
                yield collate([self._dataset[int(j)] for j in sel])
            return
        yield from self._iter_multiprocess()

    def __iter__(self):
        if not self._device_buffered():
            yield from self._iter_host()
            return
        from ..reader.prefetcher import prefetch_to_device

        pf = prefetch_to_device(self._iter_host())
        try:
            yield from pf
        finally:
            pf.close()  # early break drains in-flight device buffers

    def _iter_multiprocess(self):
        """Fan out to worker processes; results are reordered so batch
        order matches the single-process loader."""
        ctx = mp.get_context("fork")
        n_workers = self._num_workers
        index_queues = [ctx.Queue() for _ in range(n_workers)]
        result_queue = ctx.Queue()
        workers = [
            ctx.Process(target=_worker_loop,
                        args=(self._dataset, self._collate, index_queues[w],
                              result_queue, self._worker_init_fn, w),
                        daemon=True)
            for w in range(n_workers)
        ]
        for w in workers:
            w.start()
        try:
            # bounded dispatch: at most prefetch_window index batches are
            # outstanding, so results (and the reorder buffer) stay
            # O(window) rather than O(epoch) when the consumer is slower
            # than the workers (reference: _DataLoaderIter prefetch depth)
            prefetch_window = 2 * n_workers
            batch_iter = enumerate(self._batches())
            sent = 0
            exhausted = False

            def dispatch_one():
                nonlocal sent, exhausted
                if exhausted:
                    return
                try:
                    batch_idx, sel = next(batch_iter)
                except StopIteration:
                    exhausted = True
                    for q in index_queues:
                        q.put(None)
                    return
                index_queues[batch_idx % n_workers].put(
                    (batch_idx, [int(i) for i in sel]))
                sent += 1

            for _ in range(prefetch_window):
                dispatch_one()

            reorder = {}
            next_idx = 0
            done_ids = set()
            timeout = self._timeout if self._timeout else None
            while not (exhausted and next_idx >= sent):
                if next_idx in reorder:
                    yield reorder.pop(next_idx)
                    next_idx += 1
                    dispatch_one()
                    continue
                try:
                    batch_idx, data, err = result_queue.get(
                        timeout=timeout or 5.0)
                except _queue.Empty:
                    if timeout:
                        raise RuntimeError(
                            "DataLoader timed out after %ss" % timeout)
                    dead = [w.pid for wid, w in enumerate(workers)
                            if wid not in done_ids and not w.is_alive()]
                    if dead:
                        raise RuntimeError(
                            "DataLoader worker(s) %s died unexpectedly "
                            "(killed / crashed) before finishing" % dead)
                    continue
                if batch_idx is None:
                    done_ids.add(data)  # data slot carries the worker id
                    if len(done_ids) == n_workers and next_idx < sent \
                            and not reorder:
                        raise RuntimeError("DataLoader workers exited "
                                           "before producing all batches")
                    continue
                if err is not None:
                    raise RuntimeError("DataLoader worker failed: " + err)
                reorder[batch_idx] = data
        finally:
            for w in workers:
                if w.is_alive():
                    w.terminate()
            for w in workers:
                w.join()

    def __len__(self):
        if self._batch_sampler is not None:
            return len(self._batch_sampler)
        n = len(self._dataset)
        if self._drop_last:
            return n // self._batch_size
        return (n + self._batch_size - 1) // self._batch_size


class BatchSampler:
    """Reference: fluid/dataloader/batch_sampler.py BatchSampler."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self._n = len(dataset) if dataset is not None else None
        # materialize once: a generator sampler must survive repeated
        # __len__/__iter__ calls
        self._indices = list(sampler) if sampler is not None else None
        self._shuffle = shuffle
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        if self._indices is not None:
            idx = self._indices
        else:
            idx = np.arange(self._n)
            if self._shuffle:
                np.random.shuffle(idx)
        batch = []
        for i in idx:
            batch.append(int(i))
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = self._n if self._indices is None else len(self._indices)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class PyReader(_GeneratorLoader):
    """Legacy PyReader API (reference: reader.py PyReader)."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(feed_list, capacity, use_double_buffer, iterable,
                         return_list)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)
