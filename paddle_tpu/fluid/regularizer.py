"""Weight decay regularizers (reference:
`python/paddle/fluid/regularizer.py`)."""
from __future__ import annotations


class WeightDecayRegularizer:
    def _append(self, block, param, grad):
        raise NotImplementedError

    def _eager_apply(self, param, grad):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def _append(self, block, param, grad):
        # grad = grad + coeff * param  (written back onto the grad name; the
        # SSA env in lowering rebinds it)
        from .framework import unique_name

        tmp = block.create_var(name=unique_name("l2_decay"),
                               shape=param.shape, dtype=param.dtype)
        block.append_op(type="scale", inputs={"X": [param]},
                        outputs={"Out": [tmp]},
                        attrs={"scale": self._coeff, "bias": 0.0,
                               "bias_after_scale": True})
        block.append_op(type="elementwise_add",
                        inputs={"X": [grad], "Y": [tmp]},
                        outputs={"Out": [grad]}, attrs={"axis": -1})
        return grad

    def _eager_apply(self, param, grad):
        from .dygraph import base as dy_base

        out = dy_base.raw_op(
            "scale", {"X": [param._value()]},
            {"scale": self._coeff, "bias": 0.0, "bias_after_scale": True},
            ["Out"])
        summed = dy_base.raw_op(
            "elementwise_add", {"X": [grad._value()], "Y": [out[0]]},
            {"axis": -1}, ["Out"])
        return dy_base.wrap_raw(summed[0])


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def _append(self, block, param, grad):
        from .framework import unique_name

        sign = block.create_var(name=unique_name("l1_sign"),
                                shape=param.shape, dtype=param.dtype)
        block.append_op(type="sign", inputs={"X": [param]},
                        outputs={"Out": [sign]})
        block.append_op(type="scale", inputs={"X": [sign]},
                        outputs={"Out": [sign]},
                        attrs={"scale": self._coeff, "bias": 0.0,
                               "bias_after_scale": True})
        block.append_op(type="elementwise_add",
                        inputs={"X": [grad], "Y": [sign]},
                        outputs={"Out": [grad]}, attrs={"axis": -1})
        return grad


def append_regularization_ops(params_grads, regularization=None):
    out = []
    for p, g in params_grads:
        reg = getattr(p, "regularizer", None) or regularization
        if reg is not None and g is not None:
            from .framework import in_dygraph_mode

            if not in_dygraph_mode():
                reg._append(g.block, p, g)
        out.append((p, g))
    return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
