"""Preemption-aware checkpoint / auto-resume.

Reference parity: `python/paddle/fluid/incubate/fleet/collective/
__init__.py:155-341` — numbered `__paddle_fleet_checkpoint__.N`
directories holding persistables + a `fleet_train_status` JSON
(epoch_no), atomic tmp-then-move publication, redundant-checkpoint
retention, and load-latest on restart.

TPU-native design (SURVEY.md §5: TPU pods are preemptible; periodic
checkpoint + auto-resume replaces the reference's HDFS failover story):
- the on-disk layout and TrainStatus contract match the reference, with
  step_no added (TPU steps are the natural grain, not just epochs);
- saving can be ASYNC: jax arrays are immutable, so snapshotting is a
  ref-grab on the training thread; the device->host copy and file write
  happen on a background worker, overlapping the next steps (the
  reference blocks the trainer for the whole HDFS upload);
- publication is atomic (`os.replace` of a tmp dir), so a preemption
  mid-save can never leave a corrupt "latest" checkpoint.
"""
from __future__ import annotations

import json
import os
import queue
import shutil
import threading

import numpy as np

from .io import _save_dict, _load_dict, is_persistable
from ..core.scope import global_scope

_CHECKPOINT_PREFIX = "__paddle_tpu_checkpoint__"
_STATUS_FILE = "train_status.json"
_PARAM_FILE = "persistables.pkl"

__all__ = [
    "TrainStatus", "save_checkpoint", "load_checkpoint",
    "get_last_checkpoint_no", "clean_redundant_checkpoints",
    "AsyncCheckpointer", "publish_checkpoint_dir", "read_status",
    "latest_checkpoint_dir",
]


class TrainStatus:
    """Progress marker stored with each checkpoint (reference:
    collective/__init__.py:49 TrainStatus, epoch_no only; step_no and a
    free-form extra dict added)."""

    def __init__(self, epoch_no=-1, step_no=-1, extra=None):
        self._epoch_no = int(epoch_no)
        self._step_no = int(step_no)
        self._extra = dict(extra or {})

    @property
    def epoch_no(self):
        return self._epoch_no

    @property
    def step_no(self):
        return self._step_no

    @property
    def extra(self):
        return self._extra

    def next(self):
        """First epoch still to run (reference semantics: epoch_no is the
        last COMPLETED epoch)."""
        return self._epoch_no + 1

    def __eq__(self, t):
        return (isinstance(t, TrainStatus)
                and self._epoch_no == t._epoch_no
                and self._step_no == t._step_no)

    def __ne__(self, t):
        return not self == t

    def _to_dict(self):
        return {"epoch_no": self._epoch_no, "step_no": self._step_no,
                "extra": self._extra}

    @staticmethod
    def _from_dict(d):
        return TrainStatus(d.get("epoch_no", -1), d.get("step_no", -1),
                           d.get("extra"))


def _ckpt_dirs(root):
    out = {}
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for nm in names:
        parts = nm.split(".")
        if len(parts) != 2 or parts[0] != _CHECKPOINT_PREFIX:
            continue
        try:
            out[int(parts[1])] = os.path.join(root, nm)
        except ValueError:
            continue
    return out


def get_last_checkpoint_no(root):
    """Largest published checkpoint number under root, or -1."""
    nos = _ckpt_dirs(root)
    return max(nos) if nos else -1


def clean_redundant_checkpoints(root, checkpoint_num=1):
    """Keep the newest `checkpoint_num` numbered dirs (reference:
    clean_redundant_checkpoints, collective/__init__.py:206)."""
    checkpoint_num = max(int(checkpoint_num), 1)
    dirs = _ckpt_dirs(root)
    if not dirs:
        return
    cutoff = max(dirs) - checkpoint_num
    for n, path in dirs.items():
        if n <= cutoff:
            shutil.rmtree(path, ignore_errors=True)


def _snapshot(main_program, scope=None):
    """Snapshot the program's persistables NOW as device-side COPIES
    (async-dispatched HBM copy, ~ms): the executor donates state buffers
    into the next step, so holding the original refs across steps would
    read deleted arrays. The device->host transfer still happens on the
    writer thread."""
    import jax
    import jax.numpy as jnp

    from . import framework

    from ..parallel.sharded_update import unshard_scope_value

    program = main_program or framework.default_main_program()
    scope = scope or global_scope()
    snap = {}
    for var in program.list_vars():
        if is_persistable(var):
            v = scope.find_var(var.name)
            if v is None:
                continue
            # ZeRO-1 state lives as flat dp-sharded buffers; checkpoint
            # it at its logical shape so restores work regardless of
            # the flag/mesh the resuming run uses
            logical = unshard_scope_value(program, var.name, v)
            if logical is not v:
                snap[var.name] = np.asarray(logical)
                continue
            snap[var.name] = (jnp.copy(v) if isinstance(v, jax.Array)
                              else np.array(v, copy=True))
    return snap


def publish_checkpoint_dir(root, write_fn, train_status, checkpoint_num):
    """Atomic numbered publication: `write_fn(tmp_dir)` materializes the
    payload into a tmp dir, which is then os.replace'd to
    `<root>/<prefix>.<N+1>` with the TrainStatus JSON beside it — a
    preemption mid-save can never leave a corrupt latest checkpoint."""
    os.makedirs(root, exist_ok=True)
    n = get_last_checkpoint_no(root) + 1
    real = os.path.join(root, "%s.%d" % (_CHECKPOINT_PREFIX, n))
    tmp = real + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    write_fn(tmp)
    with open(os.path.join(tmp, _STATUS_FILE), "w") as f:
        json.dump(train_status._to_dict(), f)
    # injection point for the preemption-mid-save tests: a PADDLE_FAULTS
    # kill here (payload written, publication pending) leaves only the
    # .tmp dir, which _ckpt_dirs never lists — restore must fall back
    # to the previous published step, never see a half-written one
    from ..distributed import faults

    faults.on_message("ckpt", "write", method="fluid_publish")
    os.replace(tmp, real)
    if checkpoint_num:
        clean_redundant_checkpoints(root, checkpoint_num)
    try:
        from ..observability.registry import registry

        registry().event("checkpoint", action="save", path=real,
                         step_no=int(getattr(train_status, "step_no",
                                             -1) or -1))
    except Exception:  # noqa: BLE001 - telemetry only
        pass
    return real


def read_status(ckpt_dir):
    """TrainStatus of one published checkpoint dir."""
    with open(os.path.join(ckpt_dir, _STATUS_FILE)) as f:
        return TrainStatus._from_dict(json.load(f))


def latest_checkpoint_dir(root):
    """Path of the newest published checkpoint under root, or None."""
    n = get_last_checkpoint_no(root)
    if n < 0:
        return None
    return os.path.join(root, "%s.%d" % (_CHECKPOINT_PREFIX, n))


def _write_checkpoint(root, snap, train_status, checkpoint_num):
    return publish_checkpoint_dir(
        root,
        lambda tmp: _save_dict(
            tmp, {k: np.asarray(v) for k, v in snap.items()},
            _PARAM_FILE),
        train_status, checkpoint_num)


def save_checkpoint(executor, path, train_status=None, main_program=None,
                    checkpoint_num=3, scope=None):
    """Synchronous numbered checkpoint of all persistables (parameters +
    optimizer state + BN stats) with TrainStatus. Reference:
    save_checkpoint collective/__init__.py:236."""
    snap = _snapshot(main_program, scope)
    return _write_checkpoint(path, snap, train_status or TrainStatus(),
                             checkpoint_num)


def load_checkpoint(executor, path, main_program=None, scope=None,
                    ignore_empty=True, group=None):
    """Restore the LATEST intact numbered checkpoint; returns its
    TrainStatus, or None when no checkpoint exists (reference:
    load_checkpoint collective/__init__.py:294).

    Crash safety: publication is atomic (tmp-then-os.replace), but disk
    faults or a kill inside the payload write of a FUTURE publisher can
    still leave the newest dir unreadable. Rather than dying — or
    silently restarting from scratch — restore falls back to the next
    newest checkpoint that loads cleanly, logging what was skipped.

    Multi-trainer jobs (per-rank checkpoint dirs or shards): pass a
    host-collective `group` — or launch with PADDLE_CKPT_AGREE=1 to
    build one from the PADDLE_* env — and the ranks agree on the newest
    checkpoint number EVERY rank can load (allreduce-min protocol,
    distributed.sharded_checkpoint.agree_newest_intact), so one rank's
    corrupt newest dir can't silently diverge the replicas."""
    from . import framework

    dirs = _ckpt_dirs(path)
    program = main_program or framework.default_main_program()
    scope = scope or global_scope()
    names = [v.name for v in program.list_vars() if is_persistable(v)]
    if group is None:
        from ..distributed.sharded_checkpoint import _env_agree_group

        group = _env_agree_group()
    if group is not None:
        from ..distributed.sharded_checkpoint import agree_newest_intact

        # a rank with an EMPTY dir must still join the protocol: an
        # early return here would leave the other ranks blocked in the
        # store's gather and this rank silently training from scratch.
        # All-empty -> every rank agrees there is nothing to restore;
        # some-empty -> agree_newest_intact fails loudly on every rank
        # (its allreduce-min sees the empty rank's -1).
        newest = max(dirs) if dirs else -1
        global_newest = int(group.all_reduce(
            np.asarray([newest], np.int64), op="max")[0])
        if global_newest < 0:
            if not ignore_empty:
                raise RuntimeError(
                    "no checkpoint found under %r (on any rank)" % path)
            return None
        _, status = agree_newest_intact(
            list(dirs), lambda n: _load_one_checkpoint(
                dirs[int(n)], names, scope),
            group, what="fluid checkpoint", fatal=(_SchemaMismatch,))
        return status
    if not dirs:
        if not ignore_empty:
            raise RuntimeError("no checkpoint found under %r" % path)
        return None
    last_err = None
    for n in sorted(dirs, reverse=True):
        try:
            return _load_one_checkpoint(dirs[n], names, scope)
        except _SchemaMismatch:
            # the PROGRAM disagrees with the checkpoint (e.g. a newly
            # added persistable): every older checkpoint is equally
            # mismatched — surface the actionable error immediately
            # instead of reading gigabytes of doomed fallbacks
            raise
        except Exception as e:  # noqa: BLE001 - corrupt/partial dir
            last_err = e
            import logging

            logging.getLogger("paddle_tpu.checkpoint").warning(
                "checkpoint %s is unreadable (%s: %s); falling back to "
                "the previous one", dirs[n], type(e).__name__, e)
    raise RuntimeError(
        "no intact checkpoint under %r (tried %s)"
        % (path, [dirs[n] for n in sorted(dirs, reverse=True)])
    ) from last_err


class _SchemaMismatch(RuntimeError):
    """Checkpoint readable but var set disagrees with the program —
    not corruption, so the fallback loop must not retry older dirs."""


def _load_one_checkpoint(real, names, scope):
    """Load one published dir into scope; raises on ANY defect (missing
    vars, truncated pickle, bad status JSON) WITHOUT mutating the scope,
    so a fallback to an older checkpoint starts clean."""
    import jax.numpy as jnp

    d = _load_dict(real, names, _PARAM_FILE)
    missing = [nm for nm in names if nm not in d]
    if missing:
        raise _SchemaMismatch("checkpoint %r is missing vars %s"
                              % (real, missing))
    with open(os.path.join(real, _STATUS_FILE)) as f:
        status = TrainStatus._from_dict(json.load(f))
    for nm in names:
        scope.set_var(nm, jnp.asarray(d[nm]))
    try:
        from ..observability.registry import registry

        registry().event("checkpoint", action="restore", path=real,
                         step_no=int(getattr(status, "step_no", -1)
                                     or -1))
    except Exception:  # noqa: BLE001 - telemetry only
        pass
    return status


class AsyncCheckpointer:
    """Background checkpoint writer: `save_async` snapshots the scope on
    the caller's thread (ref-grab only) and returns immediately; a worker
    thread pays the device->host copy and file IO. At most one write is
    in flight; a save requested while busy replaces the pending one
    (newest wins — preemption wants the most recent state, not a queue).
    """

    def __init__(self, path, main_program=None, checkpoint_num=3,
                 scope=None):
        self._path = path
        self._program = main_program
        self._checkpoint_num = checkpoint_num
        self._scope = scope
        self._pending: "queue.Queue" = queue.Queue(maxsize=1)
        self._err = []
        self._done = threading.Event()
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="paddle_tpu-ckpt-writer")
        self._worker.start()

    def _run(self):
        while True:
            item = self._pending.get()
            if item is None:
                self._done.set()
                return
            snap, status = item
            try:
                _write_checkpoint(self._path, snap, status,
                                  self._checkpoint_num)
            except BaseException as e:  # noqa: BLE001 - surfaced in wait()
                self._err.append(e)

    def check(self):
        """Raise the first background write error, if any. Callers that
        keep training between saves use this to fail loudly instead of
        running for days on a checkpoint path that never works."""
        if self._err:
            raise RuntimeError(
                "background checkpoint write failed") from self._err[0]

    def save_async(self, train_status):
        self.check()
        snap = _snapshot(self._program, self._scope)
        item = (snap, train_status)
        while True:
            try:
                self._pending.put_nowait(item)
                return
            except queue.Full:
                try:  # replace the stale pending save
                    self._pending.get_nowait()
                except queue.Empty:
                    pass

    def close(self):
        """Flush pending saves and stop the worker; re-raises the first
        background error."""
        self._pending.put(None)
        self._done.wait(timeout=120.0)
        if self._err:
            raise self._err[0]
