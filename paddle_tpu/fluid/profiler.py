"""Profiler (reference: `python/paddle/fluid/profiler.py:39-255` over
`platform/profiler.cc` + CUPTI DeviceTracer).

TPU-native: the device tracer is jax.profiler (XPlane/perfetto, viewable in
TensorBoard or chrome://tracing); the `profiler(state, tracer_option,
profile_path)` context-manager API is preserved. RecordEvent maps to
jax.profiler.TraceAnnotation.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import defaultdict

# ONE lock for every counter table below: the counters are mutated from
# the main step loop AND background threads (the device prefetcher's
# producer, host-collective heartbeat/RPC handler threads, hapi's
# deferred-sync path) — the unlocked read-modify-write on the
# defaultdict's [count, total, max] lists lost updates under
# concurrency. Accumulation is a few arithmetic ops; one uncontended
# lock acquisition per event is noise next to a dispatched step.
_lock = threading.Lock()

_host_events = defaultdict(lambda: [0, 0.0, 0.0])  # name -> [count, total_s, max_s]

# chrome://tracing buffer: (name, start_us, dur_us, tid)
_trace_events = []
_trace_enabled = False

# -- step-phase counters (async pipeline observability) ---------------------
# Every Executor.run splits its wall time into these phases:
#   feed     — host-side feed prep + H2D issue (zero-ish when batches
#              arrive pre-transferred from reader/prefetcher.py)
#   dispatch — handing the jitted step to the runtime (async: returns
#              while the device still computes)
#   comm     — host blocked on cross-HOST collective coordination
#              (host_collectives barrier/allreduce/allgather: PS sync
#              barriers, checkpoint-step agreement, fleet metrics).
#              Device-tier ICI collective time is invisible to the host
#              (XLA overlaps it with compute) — for ICI evidence use
#              Executor.collective_report's per-collective byte census.
#   sync     — host blocked on device results (FLAGS_benchmark's
#              per-step block, return_numpy materialization, deferred
#              LazyFetch/hapi log-step syncs)
#   host     — everything else on the host between steps (cache lookup,
#              python overhead, PS bookkeeping)
# In a well-overlapped pipeline feed+sync+host ≈ 0 at steady state and
# dispatch-to-dispatch time ≈ device compute time.
STEP_PHASES = ("feed", "dispatch", "comm", "sync", "host")
_step_phases = defaultdict(lambda: [0, 0.0, 0.0])  # -> [count, total_s, max_s]


def record_step_phase(name, dt, t0=None):
    """Accumulate `dt` seconds into step-phase counter `name`; also
    emits a chrome-trace event ("phase/<name>") when tracing is live.
    Thread-safe: callers include the prefetcher's producer thread and
    RPC handler threads, concurrent with the main step loop."""
    with _lock:
        ev = _step_phases[name]
        ev[0] += 1
        ev[1] += dt
        ev[2] = max(ev[2], dt)
    record_step_trace(name, t0, dt)


def record_step_trace(name, t0, dt):
    """Trace-only phase event (no counter): the executor calls this at
    each timed segment with the segment's real start time, so a live
    trace shows phase/<name> spans where they actually happened; the
    per-step counter aggregation rides separately in run()'s finally."""
    if _trace_enabled and t0 is not None:
        with _lock:
            _trace_events.append(("phase/" + name, t0 * 1e6, dt * 1e6,
                                  threading.get_ident() % 100000))


def step_phase_total(name):
    """Accumulated seconds in one phase counter (0.0 when unseen) —
    the executor snapshots `comm` around each step so host time stays
    disjoint from collective time recorded by host_collectives."""
    with _lock:
        return _step_phases[name][1] if name in _step_phases else 0.0


def reset_step_phases():
    with _lock:
        _step_phases.clear()


def step_phase_summary(reset=False):
    """Per-step timing breakdown: {"steps": N, "feed_ms": avg, ...,
    "total_ms": sum of avgs}. `steps` = number of dispatches; phase
    averages are totals over that denominator, so rarely-firing phases
    (a deferred sync every log_freq steps) amortize correctly."""
    with _lock:
        steps = _step_phases["dispatch"][0] if "dispatch" in _step_phases \
            else 0
        denom = max(steps, 1)
        out = {"steps": steps}
        total = 0.0
        for name in STEP_PHASES:
            avg_ms = _step_phases[name][1] * 1e3 / denom \
                if name in _step_phases else 0.0
            out[name + "_ms"] = round(avg_ms, 3)
            total += avg_ms
        out["total_ms"] = round(total, 3)
        if "compile" in _step_phases:
            # cache-miss compiles ride outside the steady-state total so
            # they never pollute host_ms, but the summary still shows them
            out["compile_ms"] = round(
                _step_phases["compile"][1] * 1e3 / denom, 3)
        for lane in ("comm_ici", "comm_dcn", "comm_mp"):
            # hybrid-mesh comm lanes (host_collectives._comm_phase on a
            # PADDLE_NUM_PODS / PADDLE_MP_DEGREE launch): a BREAKDOWN
            # of comm_ms by interconnect tier, never added to the total
            if lane in _step_phases:
                out[lane + "_ms"] = round(
                    _step_phases[lane][1] * 1e3 / denom, 3)
        if reset:
            _step_phases.clear()
    return out


def step_phase_line():
    """ONE human-readable summary line (bench.py prints it)."""
    s = step_phase_summary()
    return ("step phases: %d steps, feed %.2fms dispatch %.2fms "
            "comm %.2fms sync %.2fms host %.2fms "
            "(host total %.2fms/step)"
            % (s["steps"], s["feed_ms"], s["dispatch_ms"], s["comm_ms"],
               s["sync_ms"], s["host_ms"], s["total_ms"]))


def event_count(name):
    """Host-event fire count (RecordEvent name) — lets tests assert sync
    cadence (e.g. hapi's deferred-fetch 'hapi/loss_sync')."""
    with _lock:
        return _host_events[name][0] if name in _host_events else 0


_native_broken = False


def _native_trace():
    """The C++ event store (core/native/src/trace_events.cc) when the
    native runtime builds; None otherwise (pure-python buffer is the
    fallback). The .so builds lazily on first use, so the first call is
    probed and any failure permanently disables the native path."""
    global _native_broken
    if _native_broken:
        return None
    try:
        from ..core.native import NativeTrace

        NativeTrace.count()   # forces the lazy build; cheap afterwards
        return NativeTrace
    except Exception:
        _native_broken = True
        return None


class RecordEvent:
    """Host-side RAII event (reference: platform/profiler.h:126);
    also emits a device trace annotation when a jax trace is active."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None
        self._ann = None
        self._nid = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        try:
            import jax.profiler

            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        return self

    def __exit__(self, *a):
        dt = time.perf_counter() - self._t0
        with _lock:
            ev = _host_events[self.name]
            ev[0] += 1
            ev[1] += dt
            ev[2] = max(ev[2], dt)
        if _trace_enabled:
            tid = threading.get_ident() % 100000
            nt = _native_trace()
            if nt is not None:
                if self._nid is None:
                    self._nid = nt.name_id(self.name)
                nt.record(self._nid, tid, int(self._t0 * 1e6),
                          int(dt * 1e6))
            else:
                with _lock:
                    _trace_events.append((self.name, self._t0 * 1e6,
                                          dt * 1e6, tid))
        if self._ann is not None:
            self._ann.__exit__(*a)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option="Default"):
    """Context manager (reference: profiler.py:255). Writes a jax trace to
    profile_path (a directory) viewable in TensorBoard."""
    started = False
    try:
        import jax.profiler

        os.makedirs(profile_path, exist_ok=True)
        jax.profiler.start_trace(profile_path)
        started = True
    except Exception:
        pass
    global _trace_enabled
    _trace_enabled = True
    nt = _native_trace()
    if nt is not None:
        nt.enable(True)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        wall = time.perf_counter() - t0
        _trace_enabled = False
        if started:
            import jax.profiler

            jax.profiler.stop_trace()
        export_chrome_tracing(os.path.join(profile_path,
                                           "paddle_tpu_trace.json"))
        if sorted_key:
            print_profiler_summary(wall)


def start_profiler(state="All", tracer_option="Default",
                   profile_path="/tmp/profile"):
    import jax.profiler

    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    import jax.profiler

    jax.profiler.stop_trace()


def reset_profiler():
    with _lock:
        _host_events.clear()
        _step_phases.clear()
        del _trace_events[:]
    nt = _native_trace()
    if nt is not None:
        nt.reset()


def export_chrome_tracing(path):
    """chrome://tracing JSON export (reference: tools/timeline.py:32
    converting profiler.proto records; here the host RecordEvent buffer
    plus per-event complete ("ph":"X") entries)."""
    import json

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    nt = _native_trace()
    if nt is not None and nt.count() > 0:
        # the C++ writer streams the JSON (no python loop per event)
        if nt.export(path) == 0:
            return path
        raise OSError("chrome-trace export failed to open %r" % path)
    with _lock:
        trace_events = list(_trace_events)
    events = [{"name": name, "ph": "X", "pid": 0, "tid": tid,
               "ts": ts, "dur": dur, "cat": "host"}
              for name, ts, dur, tid in trace_events]
    data = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(data, f)
    return path


def profiler_summary_rows():
    """Per-event (name, calls, total_ms, avg_ms, max_ms) rows."""
    with _lock:
        host_events = {k: list(v) for k, v in _host_events.items()}
    rows = []
    for name, (cnt, total, mx) in sorted(host_events.items(),
                                         key=lambda kv: -kv[1][1]):
        rows.append((name, cnt, total * 1e3, total * 1e3 / max(cnt, 1),
                     mx * 1e3))
    return rows


def print_profiler_summary(wall=None):
    print("%-40s %10s %12s %12s %12s" % ("Event", "Calls", "Total(ms)",
                                         "Avg(ms)", "Max(ms)"))
    for name, cnt, total, avg, mx in profiler_summary_rows()[:50]:
        print("%-40s %10d %12.3f %12.3f %12.3f" % (name, cnt, total,
                                                   avg, mx))
    if wall is not None:
        print("wall: %.3f s" % wall)


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """nvprof shim — no-op on TPU; kept for script compatibility."""
    yield


def npu_profiler(*a, **k):
    return cuda_profiler()
