"""Profiler (reference: `python/paddle/fluid/profiler.py:39-255` over
`platform/profiler.cc` + CUPTI DeviceTracer).

TPU-native: the device tracer is jax.profiler (XPlane/perfetto, viewable in
TensorBoard or chrome://tracing); the `profiler(state, tracer_option,
profile_path)` context-manager API is preserved. RecordEvent maps to
jax.profiler.TraceAnnotation.
"""
from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict

_host_events = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]


class RecordEvent:
    """Host-side RAII event (reference: platform/profiler.h:126);
    also emits a device trace annotation when a jax trace is active."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None
        self._ann = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        try:
            import jax.profiler

            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        return self

    def __exit__(self, *a):
        dt = time.perf_counter() - self._t0
        ev = _host_events[self.name]
        ev[0] += 1
        ev[1] += dt
        if self._ann is not None:
            self._ann.__exit__(*a)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option="Default"):
    """Context manager (reference: profiler.py:255). Writes a jax trace to
    profile_path (a directory) viewable in TensorBoard."""
    started = False
    try:
        import jax.profiler

        os.makedirs(profile_path, exist_ok=True)
        jax.profiler.start_trace(profile_path)
        started = True
    except Exception:
        pass
    t0 = time.perf_counter()
    try:
        yield
    finally:
        wall = time.perf_counter() - t0
        if started:
            import jax.profiler

            jax.profiler.stop_trace()
        if sorted_key:
            print_profiler_summary(wall)


def start_profiler(state="All", tracer_option="Default",
                   profile_path="/tmp/profile"):
    import jax.profiler

    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    import jax.profiler

    jax.profiler.stop_trace()


def reset_profiler():
    _host_events.clear()


def print_profiler_summary(wall=None):
    rows = sorted(_host_events.items(), key=lambda kv: -kv[1][1])
    print("%-40s %10s %14s" % ("Event", "Calls", "Total(ms)"))
    for name, (cnt, total) in rows[:50]:
        print("%-40s %10d %14.3f" % (name, cnt, total * 1e3))
    if wall is not None:
        print("wall: %.3f s" % wall)


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """nvprof shim — no-op on TPU; kept for script compatibility."""
    yield


def npu_profiler(*a, **k):
    return cuda_profiler()
