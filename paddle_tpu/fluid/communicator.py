"""fluid.communicator — user handle on the trainer-side PS
communicator (reference: `python/paddle/fluid/communicator.py:27`
wrapping the C++ Communicator of `operators/distributed/
communicator.h:176-395`). TPU-native: the real machinery is
`distributed/ps.PSCommunicator`, created lazily by the Executor from
the transpiled program's `_ps_cfg`; this class gives it the reference's
start/stop lifecycle surface."""
from __future__ import annotations


class Communicator:
    """Wraps the PS communicator of a transpiled trainer program.

    `start()` materializes the communicator (half-async mode starts its
    background merge-send thread); `stop()` flushes and joins it.
    """

    def __init__(self, program, mode=None, kwargs=None, envs=None):
        cfg = getattr(program, "_ps_cfg", None)
        if cfg is None:
            raise ValueError(
                "Communicator needs a program transpiled for PS "
                "training (DistributeTranspiler / strategy.a_sync)")
        if mode is not None and mode != cfg["mode"]:
            # the mode is baked into the transpiled program; accepting
            # a different one here would silently run the other mode
            raise ValueError(
                "Communicator mode %r does not match the program's "
                "transpiled mode %r — re-transpile with the mode you "
                "want" % (mode, cfg["mode"]))
        self._program = program
        self._mode = cfg["mode"]
        self._comm = None

    def start(self):
        from ..distributed.ps import PSCommunicator

        if self._comm is None:
            self._comm = PSCommunicator(self._program._ps_cfg)
            # the executor reuses an existing communicator instance
            # instead of building its own
            self._program._ps_comm = self._comm

    def stop(self):
        if self._comm is not None:
            # complete() is PSCommunicator's shutdown: flushes pending
            # half-async rounds, joins the sender thread, and tells the
            # pservers this trainer is done (same call the Executor's
            # own close path makes)
            self._comm.complete()
            self._comm = None
            self._program._ps_comm = None

    def is_running(self):
        return self._comm is not None
