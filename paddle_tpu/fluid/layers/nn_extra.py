"""fluid.layers builders, second tranche (reference:
`python/paddle/fluid/layers/nn.py` remainder): interpolation/resize
wrappers, 3D conv/pool, vision rearrangement ops, RNN unit builders
(dynamic_lstm/dynamic_gru families), candidate-sampling and structured
losses, and misc helpers. Split from nn.py for maintainability; the
public surface is identical (star-imported by layers/__init__)."""
from __future__ import annotations

import numpy as np

from .. import framework
from ..param_attr import ParamAttr
from ..layer_helper import LayerHelper, apply_op
from ..initializer import ConstantInitializer

__all__ = [
    "interpolate", "resize_bilinear", "resize_trilinear", "resize_linear",
    "resize_bicubic", "image_resize_short", "pool3d", "adaptive_pool3d",
    "conv3d", "conv3d_transpose", "grid_sampler", "affine_grid",
    "affine_channel", "lrn", "unfold", "space_to_depth",
    "shuffle_channel", "temporal_shift", "pixel_shuffle", "maxout",
    "selu", "softshrink", "hard_shrink", "tanh_shrink", "brelu",
    "soft_relu", "thresholded_relu", "row_conv", "fsp_matrix", "hash",
    "add_position_encoding", "similarity_focus", "random_crop",
    "pad_constant_like", "continuous_value_model", "filter_by_instag",
    "dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "gru_unit",
    "lstm_unit", "lstm", "nce", "sampled_softmax_with_cross_entropy",
    "hsigmoid", "warpctc", "linear_chain_crf", "crf_decoding",
    "im2sequence", "multiplex", "dice_loss", "log_loss", "npair_loss",
    "rank_loss", "margin_rank_loss", "bpr_loss", "center_loss",
    "teacher_student_sigmoid_loss", "sigmoid_focal_loss", "cos_sim",
    "deformable_conv", "unpool", "spectral_norm", "sampling_id",
    "py_func", "shard_index", "uniform_random_batch_size_like",
]


def _one(op, inputs, attrs, slot="Out", dtype=None, helper=None):
    return apply_op(helper or op, op, inputs, attrs, [slot],
                    out_dtype=dtype)[0]


# -- interpolation ----------------------------------------------------------

_RESAMPLE_OP = {"NEAREST": "nearest_interp", "BILINEAR": "bilinear_interp",
                "TRILINEAR": "trilinear_interp", "BICUBIC": "bicubic_interp",
                "LINEAR": "linear_interp"}


def interpolate(input, out_shape=None, scale=None, name=None,
                resample="BILINEAR", actual_shape=None, align_corners=True,
                align_mode=1, data_format="NCHW"):
    """reference layers/nn.py interpolate → the *_interp op family. The
    OutSize tensor path is folded to static ints (XLA static shapes)."""
    op_type = _RESAMPLE_OP[resample.upper()]
    attrs = {"align_corners": align_corners, "align_mode": align_mode,
             "data_layout": data_format}
    shape = out_shape if out_shape is not None else actual_shape
    if shape is not None:
        dims = [int(d) for d in (
            shape.tolist() if hasattr(shape, "tolist") else shape)]
        keys = {1: ["out_w"], 2: ["out_h", "out_w"],
                3: ["out_d", "out_h", "out_w"]}[len(dims)]
        attrs.update(dict(zip(keys, dims)))
    elif scale is not None:
        attrs["scale"] = float(scale)
    else:
        raise ValueError("interpolate needs out_shape or scale")
    return _one(op_type, {"X": [input]}, attrs)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    return interpolate(input, out_shape, scale, name, "BILINEAR",
                       actual_shape, align_corners, align_mode,
                       data_format)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    return interpolate(input, out_shape, scale, name, "TRILINEAR",
                       actual_shape, align_corners, align_mode,
                       data_format)


def resize_linear(input, out_shape=None, scale=None, name=None,
                  align_corners=True, align_mode=1, data_format="NCW"):
    return interpolate(input, out_shape, scale, name, "LINEAR", None,
                       align_corners, align_mode, data_format)


def resize_bicubic(input, out_shape=None, scale=None, name=None,
                   align_corners=True, data_format="NCHW"):
    return interpolate(input, out_shape, scale, name, "BICUBIC", None,
                       align_corners, 0, data_format)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    h, w = input.shape[2], input.shape[3]
    short, long_ = (h, w) if h < w else (w, h)
    ratio = out_short_len / float(short)
    out_shape = ([out_short_len, int(long_ * ratio)] if h < w
                 else [int(long_ * ratio), out_short_len])
    return interpolate(input, out_shape=out_shape, resample=resample)


# -- 3d conv/pool -----------------------------------------------------------

def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, name=None):
    def _triple(v):
        return [v] * 3 if isinstance(v, int) else list(v)
    if global_pooling:
        pool_size = list(input.shape[2:])
        pool_padding = 0
    return _one("pool3d", {"X": [input]},
                {"ksize": _triple(pool_size),
                 "pooling_type": pool_type,
                 "strides": _triple(pool_stride),
                 "paddings": _triple(pool_padding)})


def adaptive_pool3d(input, pool_size, pool_type="max", name=None):
    d, h, w = input.shape[2:]
    ps = [pool_size] * 3 if isinstance(pool_size, int) else list(pool_size)
    assert d % ps[0] == 0 and h % ps[1] == 0 and w % ps[2] == 0, \
        "adaptive_pool3d needs divisible spatial dims"
    k = [d // ps[0], h // ps[1], w // ps[2]]
    return _one("pool3d", {"X": [input]},
                {"ksize": k, "pooling_type": pool_type, "strides": k,
                 "paddings": [0, 0, 0]})


def _conv_nd(op_type, input, num_filters, filter_size, stride, padding,
             dilation, groups, param_attr, bias_attr, act, name, nd,
             transpose=False):
    helper = LayerHelper(op_type, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)

    def _tup(v):
        return [v] * nd if isinstance(v, int) else list(v)

    c_in = input.shape[1]
    groups = groups or 1
    if transpose:
        w_shape = [c_in, num_filters // groups] + _tup(filter_size)
    else:
        w_shape = [num_filters, c_in // groups] + _tup(filter_size)
    w = helper.create_parameter(helper.param_attr, shape=w_shape,
                                dtype=input.dtype)
    out = apply_op(helper, op_type,
                   {"Input": [input], "Filter": [w]},
                   {"strides": _tup(stride), "paddings": _tup(padding),
                    "dilations": _tup(dilation), "groups": groups},
                   ["Output"], out_dtype=input.dtype)[0]
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, shape=[num_filters], dtype=input.dtype,
            is_bias=True)
        out = _one("elementwise_add", {"X": [out], "Y": [b]},
                   {"axis": 1}, dtype=input.dtype, helper=helper)
    return helper.append_activation(out)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None):
    return _conv_nd("conv3d", input, num_filters, filter_size, stride,
                    padding, dilation, groups, param_attr, bias_attr,
                    act, name, 3)


def conv3d_transpose(input, num_filters, output_size=None,
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=None, param_attr=None, bias_attr=None,
                     use_cudnn=True, act=None, name=None):
    return _conv_nd("conv3d_transpose", input, num_filters, filter_size,
                    stride, padding, dilation, groups, param_attr,
                    bias_attr, act, name, 3, transpose=True)


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=None,
                    deformable_groups=None, im2col_step=None,
                    param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    helper = LayerHelper("deformable_conv", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)

    def _pair(v):
        return [v] * 2 if isinstance(v, int) else list(v)

    groups = groups or 1
    c_in = input.shape[1]
    w = helper.create_parameter(
        helper.param_attr,
        shape=[num_filters, c_in // groups] + _pair(filter_size),
        dtype=input.dtype)
    op = "deformable_conv" if modulated else "deformable_conv_v1"
    ins = {"Input": [input], "Offset": [offset], "Filter": [w]}
    if modulated and mask is not None:
        ins["Mask"] = [mask]
    return apply_op(helper, op, ins,
                    {"strides": _pair(stride), "paddings": _pair(padding),
                     "dilations": _pair(dilation), "groups": groups,
                     "deformable_groups": deformable_groups or 1},
                    ["Output"], out_dtype=input.dtype)[0]


def unpool(input, indices, unpool_size=None, name=None):
    oh, ow = unpool_size if unpool_size else (
        input.shape[2] * 2, input.shape[3] * 2)
    return _one("unpool", {"X": [input], "Indices": [indices]},
                {"unpooled_height": oh, "unpooled_width": ow})


# -- vision helpers ----------------------------------------------------------

def grid_sampler(x, grid, name=None):
    return _one("grid_sampler", {"X": [x], "Grid": [grid]}, {},
                "Output")


def affine_grid(theta, out_shape=None, name=None):
    attrs = {}
    if out_shape is not None and not isinstance(out_shape, framework.Variable):
        attrs["output_shape"] = [int(v) for v in out_shape]
    return _one("affine_grid", {"Theta": [theta]}, attrs, "Output")


def affine_channel(x, scale=None, bias=None, data_layout="NCHW",
                   name=None, act=None):
    from .tensor import fill_constant

    c = x.shape[1 if data_layout == "NCHW" else -1]
    if scale is None:
        scale = fill_constant([c], x.dtype, 1.0)
    if bias is None:
        bias = fill_constant([c], x.dtype, 0.0)
    out = _one("affine_channel",
               {"X": [x], "Scale": [scale], "Bias": [bias]},
               {"data_layout": data_layout})
    helper = LayerHelper("affine_channel", act=act)
    return helper.append_activation(out)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    return _one("lrn", {"X": [input]},
                {"n": n, "k": k, "alpha": alpha, "beta": beta,
                 "data_format": data_format})


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return [v] * 2 if isinstance(v, int) else list(v)
    pads = _pair(paddings)
    if len(pads) == 2:
        pads = pads + pads
    return _one("unfold", {"X": [x]},
                {"kernel_sizes": _pair(kernel_sizes),
                 "strides": _pair(strides), "paddings": pads,
                 "dilations": _pair(dilations)}, "Y")


def space_to_depth(x, blocksize, name=None):
    return _one("space_to_depth", {"X": [x]}, {"blocksize": blocksize})


def shuffle_channel(x, group, name=None):
    return _one("shuffle_channel", {"X": [x]}, {"group": group})


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _one("temporal_shift", {"X": [x]},
                {"seg_num": seg_num, "shift_ratio": shift_ratio})


def pixel_shuffle(x, upscale_factor):
    return _one("pixel_shuffle", {"X": [x]},
                {"upscale_factor": upscale_factor})


def maxout(x, groups, name=None, axis=1):
    return _one("maxout", {"X": [x]}, {"groups": groups, "axis": axis})


def _act_wrapper(op_type, attr_names=()):
    def fn(x, *args, **kwargs):
        attrs = {}
        for i, a in enumerate(args):
            attrs[attr_names[i]] = a
        for k, v in kwargs.items():
            if k in attr_names:
                attrs[k] = v
        return _one(op_type, {"X": [x]}, attrs)
    fn.__name__ = op_type
    return fn


selu = _act_wrapper("selu", ("scale", "alpha"))
softshrink = _act_wrapper("softshrink", ("lambda",))
hard_shrink = _act_wrapper("hard_shrink", ("threshold",))
tanh_shrink = _act_wrapper("tanh_shrink")
brelu = _act_wrapper("brelu", ("t_min", "t_max"))
soft_relu = _act_wrapper("soft_relu", ("threshold",))
thresholded_relu = _act_wrapper("thresholded_relu", ("threshold",))


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    d = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                shape=[future_context_size + 1, d],
                                dtype=input.dtype)
    out = apply_op(helper, "row_conv",
                   {"X": [input], "Filter": [w]}, {}, ["Out"],
                   out_dtype=input.dtype)[0]
    return helper.append_activation(out)


def fsp_matrix(x, y):
    return _one("fsp", {"X": [x], "Y": [y]}, {})


def hash(input, hash_size, num_hash=1, name=None):
    return _one("hash", {"X": [input]},
                {"mod_by": hash_size, "num_hash": num_hash},
                dtype="int64")


def add_position_encoding(input, alpha, beta, name=None):
    return _one("add_position_encoding", {"X": [input]},
                {"alpha": alpha, "beta": beta})


def similarity_focus(input, axis, indexes, name=None):
    return _one("similarity_focus", {"X": [input]},
                {"axis": axis, "indexes": list(indexes)})


def random_crop(x, shape, seed=None):
    return _one("random_crop", {"X": [x]}, {"shape": list(shape)})


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _one("pad_constant_like", {"X": [x], "Y": [y]},
                {"pad_value": pad_value})


def continuous_value_model(input, cvm, use_cvm=True):
    return _one("cvm", {"X": [input], "CVM": [cvm]},
                {"use_cvm": use_cvm}, "Y")


def filter_by_instag(ins, ins_tag, filter_tag, is_lod, out_val_if_empty=0):
    outs = apply_op("filter_by_instag", "filter_by_instag",
                    {"Ins": [ins], "Ins_tag": [ins_tag],
                     "Filter_tag": [filter_tag]},
                    {"is_lod": is_lod}, ["Out", "LossWeight", "IndexMap"])
    return outs[0], outs[1], outs[2]


# -- rnn units --------------------------------------------------------------

def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """reference layers/nn.py dynamic_lstm: input [B, T, 4D] is the
    pre-projected gate input; creates Weight [D, 4D] and Bias."""
    helper = LayerHelper("lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d = size // 4
    w = helper.create_parameter(helper.param_attr, shape=[d, 4 * d],
                                dtype=dtype)
    b_len = 7 * d if use_peepholes else 4 * d
    b = helper.create_parameter(helper.bias_attr, shape=[1, b_len],
                                dtype=dtype, is_bias=True)
    ins = {"Input": [input], "Weight": [w], "Bias": [b]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    outs = apply_op(helper, "lstm", ins,
                    {"use_peepholes": use_peepholes,
                     "is_reverse": is_reverse,
                     "gate_activation": gate_activation,
                     "cell_activation": cell_activation,
                     "candidate_activation": candidate_activation},
                    ["Hidden", "Cell"], out_dtype=dtype)
    return outs[0], outs[1]


def dynamic_lstmp(input, size, proj_size, h_0=None, c_0=None,
                  param_attr=None, bias_attr=None, use_peepholes=True,
                  is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="tanh", dtype="float32", name=None):
    helper = LayerHelper("lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d = size // 4
    w = helper.create_parameter(helper.param_attr,
                                shape=[proj_size, 4 * d], dtype=dtype)
    w_proj = helper.create_parameter(helper.param_attr,
                                     shape=[d, proj_size], dtype=dtype)
    b_len = 7 * d if use_peepholes else 4 * d
    b = helper.create_parameter(helper.bias_attr, shape=[1, b_len],
                                dtype=dtype, is_bias=True)
    ins = {"Input": [input], "Weight": [w], "ProjWeight": [w_proj],
           "Bias": [b]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    outs = apply_op(helper, "lstmp", ins,
                    {"use_peepholes": use_peepholes,
                     "is_reverse": is_reverse,
                     "gate_activation": gate_activation,
                     "cell_activation": cell_activation,
                     "candidate_activation": candidate_activation,
                     "proj_activation": proj_activation},
                    ["Projection", "Cell"], out_dtype=dtype)
    return outs[0], outs[1]


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None,
                origin_mode=False):
    helper = LayerHelper("gru", param_attr=param_attr,
                         bias_attr=bias_attr)
    dtype = input.dtype
    w = helper.create_parameter(helper.param_attr, shape=[size, 3 * size],
                                dtype=dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[1, 3 * size],
                                dtype=dtype, is_bias=True)
    ins = {"Input": [input], "Weight": [w], "Bias": [b]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    return apply_op(helper, "gru", ins,
                    {"is_reverse": is_reverse,
                     "gate_activation": gate_activation,
                     "activation": candidate_activation,
                     "origin_mode": origin_mode},
                    ["Hidden", "BatchGate", "BatchResetHiddenPrev",
                     "BatchHidden"], out_dtype=dtype)[0]


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    dtype = input.dtype
    d = size // 3
    w = helper.create_parameter(helper.param_attr, shape=[d, 3 * d],
                                dtype=dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[1, 3 * d],
                                dtype=dtype, is_bias=True)
    outs = apply_op(helper, "gru_unit",
                    {"Input": [input], "HiddenPrev": [hidden],
                     "Weight": [w], "Bias": [b]},
                    {"activation": activation,
                     "gate_activation": gate_activation,
                     "origin_mode": origin_mode},
                    ["Hidden", "Gate", "ResetHiddenPrev"],
                    out_dtype=dtype)
    return outs[0], outs[2], outs[1]


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """reference layers/nn.py lstm_unit: fc([x, h]) → lstm_unit op."""
    from .nn import fc
    from .tensor import concat
    d = cell_t_prev.shape[-1]
    merged = concat([x_t, hidden_t_prev], axis=1)
    gates = fc(merged, 4 * d, param_attr=param_attr, bias_attr=bias_attr)
    outs = apply_op("lstm_unit", "lstm_unit",
                    {"X": [gates], "C_prev": [cell_t_prev]},
                    {"forget_bias": forget_bias}, ["C", "H"],
                    out_dtype=x_t.dtype)
    return outs[1], outs[0]


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """reference layers/nn.py lstm (the cudnn_lstm builder): input
    [T, B, D] time-major."""
    helper = LayerHelper("cudnn_lstm", name=name)
    d_in = input.shape[-1]
    n_dir = 2 if is_bidirec else 1
    sz = 0
    d_cur = d_in
    for _ in range(num_layers):
        sz += n_dir * (4 * hidden_size * d_cur
                       + 4 * hidden_size * hidden_size + 8 * hidden_size)
        d_cur = hidden_size * n_dir
    w = helper.create_parameter(
        ParamAttr(initializer=default_initializer)
        if default_initializer else None,
        shape=[sz], dtype=input.dtype)
    outs = apply_op(helper, "cudnn_lstm",
                    {"Input": [input], "W": [w], "InitH": [init_h],
                     "InitC": [init_c]},
                    {"hidden_size": hidden_size, "num_layers": num_layers,
                     "is_bidirec": is_bidirec},
                    ["Out", "last_h", "last_c"], out_dtype=input.dtype)
    return outs[0], outs[1], outs[2]


# -- sampling / structured losses -------------------------------------------

def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    helper = LayerHelper("nce", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = input.dtype
    d = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                shape=[num_total_classes, d], dtype=dtype)
    b = helper.create_parameter(helper.bias_attr,
                                shape=[num_total_classes], dtype=dtype,
                                is_bias=True)
    sampler_id = {"uniform": 0, "log_uniform": 1,
                  "custom_dist": 2}[sampler]
    outs = apply_op(helper, "nce",
                    {"Input": [input], "Label": [label], "Weight": [w],
                     "Bias": [b]},
                    {"num_neg_samples": num_neg_samples or 10,
                     "sampler": sampler_id, "seed": seed},
                    ["Cost", "SampleLogits", "SampleLabels"],
                    out_dtype=dtype)
    return outs[0]


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1,
                                       remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    outs = apply_op("sampled_softmax_with_cross_entropy",
                    "sampled_softmax_with_cross_entropy",
                    {"Logits": [logits], "Label": [label]},
                    {"num_samples": num_samples,
                     "remove_accidental_hits": remove_accidental_hits,
                     "seed": seed}, ["Loss", "Softmax"])
    return outs[0]


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    helper = LayerHelper("hsigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = input.dtype
    d = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                shape=[num_classes - 1, d], dtype=dtype)
    b = helper.create_parameter(helper.bias_attr,
                                shape=[num_classes - 1], dtype=dtype,
                                is_bias=True)
    outs = apply_op(helper, "hsigmoid",
                    {"X": [input], "W": [w], "Label": [label],
                     "Bias": [b]},
                    {"num_classes": num_classes}, ["Out", "PreOut"],
                    out_dtype=dtype)
    return outs[0]


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    ins = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        ins["LogitsLength"] = [input_length]
    if label_length is not None:
        ins["LabelLength"] = [label_length]
    return _one("warpctc", ins,
                {"blank": blank, "norm_by_times": norm_by_times},
                "Loss")


def linear_chain_crf(input, label, param_attr=None, length=None):
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    k = input.shape[-1]
    w = helper.create_parameter(helper.param_attr, shape=[k + 2, k],
                                dtype=input.dtype)
    ins = {"Emission": [input], "Transition": [w], "Label": [label]}
    if length is not None:
        ins["Length"] = [length]
    outs = apply_op(helper, "linear_chain_crf", ins, {},
                    ["LogLikelihood", "Alpha", "EmissionExps",
                     "TransitionExps"], out_dtype=input.dtype)
    return outs[0]


def crf_decoding(input, param_attr, label=None, length=None):
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    # reuse the transition parameter created by linear_chain_crf via
    # param_attr.name
    from ..framework import default_main_program
    name = param_attr.name if param_attr is not None and \
        getattr(param_attr, "name", None) else None
    blk = default_main_program().global_block()
    if name and name in blk.vars:
        w = blk.vars[name]
    else:
        k = input.shape[-1]
        w = helper.create_parameter(helper.param_attr, shape=[k + 2, k],
                                    dtype=input.dtype)
    ins = {"Emission": [input], "Transition": [w]}
    if label is not None:
        ins["Label"] = [label]
    if length is not None:
        ins["Length"] = [length]
    return apply_op(helper, "crf_decoding", ins, {}, ["ViterbiPath"],
                    out_dtype="int64")[0]


def im2sequence(input, filter_size=1, stride=1, padding=0,
                input_image_size=None, out_stride=1, name=None):
    def _pair(v):
        return [v] * 2 if isinstance(v, int) else list(v)
    pads = _pair(padding)
    if len(pads) == 2:
        pads = pads + pads
    return _one("im2sequence", {"X": [input]},
                {"kernels": _pair(filter_size),
                 "strides": _pair(stride), "paddings": pads})


def multiplex(inputs, index):
    return _one("multiplex", {"X": list(inputs), "Ids": [index]}, {})


# -- small losses ------------------------------------------------------------

def dice_loss(input, label, epsilon=1e-5):
    return _one("dice_loss", {"X": [input], "Label": [label]},
                {"epsilon": epsilon})


def log_loss(input, label, epsilon=1e-4, name=None):
    return _one("log_loss", {"Predicted": [input], "Labels": [label]},
                {"epsilon": epsilon}, "Loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    return _one("npair_loss",
                {"Anchor": [anchor], "Positive": [positive],
                 "Labels": [labels]}, {"l2_reg": l2_reg})


def rank_loss(label, left, right, name=None):
    return _one("rank_loss",
                {"Label": [label], "Left": [left], "Right": [right]}, {})


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    return _one("margin_rank_loss",
                {"Label": [label], "X1": [left], "X2": [right]},
                {"margin": margin})


def bpr_loss(input, label, name=None):
    return _one("bpr_loss", {"X": [input], "Label": [label]}, {})


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    helper = LayerHelper("center_loss", param_attr=param_attr)
    d = input.shape[-1]
    centers = helper.create_parameter(
        helper.param_attr, shape=[num_classes, d], dtype=input.dtype,
        default_initializer=ConstantInitializer(0.0))
    outs = apply_op(helper, "center_loss",
                    {"X": [input], "Label": [label],
                     "Centers": [centers]},
                    {"cluster_num": num_classes, "alpha": alpha,
                     "need_update": update_center},
                    ["Loss", "SampleCenterDiff", "CentersOut"],
                    out_dtype=input.dtype)
    return outs[0]


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    return _one("teacher_student_sigmoid_loss",
                {"X": [input], "Label": [label]},
                {"soft_max_up_bound": soft_max_up_bound,
                 "soft_max_lower_bound": soft_max_lower_bound}, "Y")


def sigmoid_focal_loss(x, label, fg_num=None, gamma=2.0, alpha=0.25):
    from .detection import sigmoid_focal_loss as _impl
    return _impl(x, label, fg_num, gamma, alpha)


def cos_sim(X, Y):
    return _one("cos_sim", {"X": [X], "Y": [Y]}, {})


# -- misc --------------------------------------------------------------------

def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    helper = LayerHelper("spectral_norm", name=name)
    h = weight.shape[dim]
    w_dim = int(np.prod(weight.shape)) // h
    from ..initializer import NormalInitializer
    u = helper.create_parameter(None, shape=[h], dtype=weight.dtype,
                                default_initializer=NormalInitializer())
    v = helper.create_parameter(None, shape=[w_dim], dtype=weight.dtype,
                                default_initializer=NormalInitializer())
    return apply_op(helper, "spectral_norm",
                    {"Weight": [weight], "U": [u], "V": [v]},
                    {"dim": dim, "power_iters": power_iters, "eps": eps},
                    ["Out"], out_dtype=weight.dtype)[0]


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    return _one("sampling_id", {"X": [x]},
                {"min": min, "max": max, "seed": seed}, dtype="int64")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference layers/py_func: call a python function inside the
    program. `out` gives the output var(s) template."""
    from ...ops.framework_ops import register_py_func
    fid = register_py_func(func)
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    got = apply_op("py_func", "py_func", {"X": list(xs)},
                   {"func_id": fid}, {"Out": len(outs)})
    return got if len(got) > 1 else got[0]


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return _one("shard_index", {"X": [input]},
                {"index_num": index_num, "nshards": nshards,
                 "shard_id": shard_id, "ignore_value": ignore_value},
                dtype="int64")


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    return _one("uniform_random_batch_size_like", {"Input": [input]},
                {"shape": list(shape), "input_dim_idx": input_dim_idx,
                 "output_dim_idx": output_dim_idx, "min": min,
                 "max": max, "seed": seed}, dtype=dtype)
