"""fluid.layers — op wrapper namespace (reference:
`python/paddle/fluid/layers/`)."""
from . import nn, tensor, loss, collective, math_op_patch  # noqa: F401
from . import control_flow  # noqa: F401
from . import distributions  # noqa: F401
from . import rnn_decode  # noqa: F401
from .rnn_decode import (  # noqa: F401
    RNNCell, GRUCell, BeamSearchDecoder, dynamic_decode,
)


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """reference layers/rnn.py beam_search op wrapper."""
    from ..layer_helper import apply_op

    outs = apply_op("beam_search", "beam_search",
                    {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                     "ids": [ids], "scores": [scores]},
                    {"beam_size": beam_size, "end_id": end_id,
                     "level": level, "is_accumulated": is_accumulated},
                    ["selected_ids", "selected_scores", "parent_idx"])
    if return_parent_idx:
        return outs[0], outs[1], outs[2]
    return outs[0], outs[1]


def beam_search_decode(ids, scores, beam_size, end_id, name=None):
    from ..layer_helper import apply_op

    outs = apply_op("beam_search_decode", "beam_search_decode",
                    {"Ids": [ids], "Scores": [scores]},
                    {"beam_size": beam_size, "end_id": end_id},
                    ["SentenceIds", "SentenceScores"])
    return outs[0], outs[1]


def gather_tree(ids, parents):
    from ..layer_helper import apply_op

    return apply_op("gather_tree", "gather_tree",
                    {"Ids": [ids], "Parents": [parents]}, {}, ["Out"],
                    out_dtype="int64")[0]
from . import learning_rate_scheduler  # noqa: F401
from .nn import *  # noqa: F401,F403
from .nn_extra import *  # noqa: F401,F403
from . import nn_extra  # noqa: F401
from . import detection  # noqa: F401
from .detection import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .control_flow import (  # noqa: F401
    Scan, While, while_loop, cond, case, switch_case, increment,
    less_than, less_equal, greater_than, greater_equal, equal, not_equal,
    Print, Assert, StaticRNN, is_empty, reorder_lod_tensor_by_rank,
)
from .learning_rate_scheduler import (  # noqa: F401
    noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup,
)

# `data` also lives at layers top level in the reference
from .tensor import data  # noqa: F401
