"""fluid.layers — op wrapper namespace (reference:
`python/paddle/fluid/layers/`)."""
from . import nn, tensor, loss, collective, math_op_patch  # noqa: F401
from . import control_flow  # noqa: F401
from . import distributions  # noqa: F401
from . import rnn_decode  # noqa: F401
from .rnn_decode import (  # noqa: F401
    RNNCell, GRUCell, BeamSearchDecoder, dynamic_decode,
)
from . import learning_rate_scheduler  # noqa: F401
from .nn import *  # noqa: F401,F403
from .nn_extra import *  # noqa: F401,F403
from . import nn_extra  # noqa: F401
from . import detection  # noqa: F401
from .detection import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .control_flow import (  # noqa: F401
    While, while_loop, cond, case, switch_case, increment,
    less_than, less_equal, greater_than, greater_equal, equal, not_equal,
)
from .learning_rate_scheduler import (  # noqa: F401
    noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup,
)

# `data` also lives at layers top level in the reference
from .tensor import data  # noqa: F401
