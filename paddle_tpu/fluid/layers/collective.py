"""Collective op wrappers used by the fleet transpiler (reference:
`python/paddle/fluid/layers/collective.py:64-172`). On TPU these lower to
XLA collectives over ICI (see paddle_tpu/ops/collective_ops.py)."""
from __future__ import annotations

from ..layer_helper import apply_op


def _c_allreduce(x, out=None, reduce_type="sum", ring_id=0,
                 use_calc_stream=False):
    op_type = "c_allreduce_" + reduce_type
    return apply_op(op_type, op_type, {"X": [x]},
                    {"ring_id": ring_id, "use_calc_stream": use_calc_stream},
                    ["Out"], out_dtype=x.dtype)[0]


def _c_broadcast(x, root=0, ring_id=0, use_calc_stream=False):
    return apply_op("c_broadcast", "c_broadcast", {"X": [x]},
                    {"root": root, "ring_id": ring_id,
                     "use_calc_stream": use_calc_stream},
                    ["Out"], out_dtype=x.dtype)[0]


def _c_allgather(x, nranks, ring_id=0, use_calc_stream=False):
    return apply_op("c_allgather", "c_allgather", {"X": [x]},
                    {"nranks": nranks, "ring_id": ring_id,
                     "use_calc_stream": use_calc_stream},
                    ["Out"], out_dtype=x.dtype)[0]


def _c_reducescatter(x, nranks, ring_id=0, use_calc_stream=False):
    return apply_op("c_reducescatter", "c_reducescatter", {"X": [x]},
                    {"nranks": nranks, "ring_id": ring_id,
                     "use_calc_stream": use_calc_stream},
                    ["Out"], out_dtype=x.dtype)[0]


def _c_sync_calc_stream(x):
    return apply_op("c_sync_calc_stream", "c_sync_calc_stream", {"X": [x]},
                    {}, ["Out"], out_dtype=x.dtype)[0]


def _c_sync_comm_stream(x, ring_id=0):
    return apply_op("c_sync_comm_stream", "c_sync_comm_stream", {"X": [x]},
                    {"ring_id": ring_id}, ["Out"], out_dtype=x.dtype)[0]
