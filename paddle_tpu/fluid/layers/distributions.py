"""Probability distributions.

Reference parity: `python/paddle/fluid/layers/distributions.py` —
Uniform, Normal, Categorical, MultivariateNormalDiag with
sample/entropy/log_prob/kl_divergence built from layers ops. TPU-native:
pure jnp math usable in eager mode and under the static tracer (the ops
go through the same registry; sampling uses the seeded uniform/gaussian
RNG ops so static-graph runs stay deterministic per program seed).
"""
from __future__ import annotations

import math

import numpy as np

from .. import framework
from ..framework import Variable, in_dygraph_mode
from ..layer_helper import LayerHelper
from . import tensor as tensor_layers
from . import nn as nn_layers

__all__ = ["Uniform", "Normal", "Categorical", "MultivariateNormalDiag"]


def _to_var(value, name_hint="dist_const"):
    """Accept floats / numpy / Variables / eager Tensors uniformly."""
    if isinstance(value, Variable):
        return value
    if in_dygraph_mode():
        from ..dygraph import base as dy_base
        import jax.numpy as jnp

        if isinstance(value, dy_base.Tensor):
            return value
        return dy_base.Tensor(jnp.asarray(np.asarray(value, "float32")),
                              stop_gradient=True)
    arr = np.asarray(value, "float32")
    return tensor_layers.assign(arr)


class Distribution:
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """Uniform[low, high) (reference: distributions.py Uniform)."""

    def __init__(self, low, high):
        self.low = _to_var(low)
        self.high = _to_var(high)

    def sample(self, shape, seed=0):
        u = nn_layers.uniform_random(shape, min=0.0, max=1.0, seed=seed)
        return self.low + (self.high - self.low) * u

    def entropy(self):
        return nn_layers.log(self.high - self.low)

    def log_prob(self, value):
        lb = tensor_layers.cast(value > self.low, "float32")
        ub = tensor_layers.cast(value < self.high, "float32")
        return nn_layers.log(lb * ub) - nn_layers.log(
            self.high - self.low)


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)

    def sample(self, shape, seed=0):
        z = nn_layers.gaussian_random(shape, mean=0.0, std=1.0,
                                       seed=seed)
        return self.loc + self.scale * z

    def entropy(self):
        c = 0.5 + 0.5 * math.log(2.0 * math.pi)
        return c + nn_layers.log(self.scale)

    def log_prob(self, value):
        var = self.scale * self.scale
        log_scale = nn_layers.log(self.scale)
        return (-1.0 * ((value - self.loc) * (value - self.loc))
                / (2.0 * var) - log_scale
                - math.log(math.sqrt(2.0 * math.pi)))

    def kl_divergence(self, other):
        var_ratio = self.scale / other.scale
        var_ratio = var_ratio * var_ratio
        t1 = (self.loc - other.loc) / other.scale
        t1 = t1 * t1
        return 0.5 * (var_ratio + t1 - 1.0 - nn_layers.log(var_ratio))


class Categorical(Distribution):
    def __init__(self, logits):
        self.logits = logits if isinstance(logits, Variable) or \
            in_dygraph_mode() else _to_var(logits)

    def _probs(self):
        return nn_layers.softmax(self.logits)

    def entropy(self):
        p = self._probs()
        lp = nn_layers.log(p + 1e-12)
        neg = nn_layers.reduce_sum(p * lp, dim=-1)
        return -1.0 * neg

    def log_prob(self, value):
        p = self._probs()
        onehot = nn_layers.one_hot(value,
                                   depth=int(self.logits.shape[-1]))
        return nn_layers.log(
            nn_layers.reduce_sum(p * onehot, dim=-1) + 1e-12)

    def kl_divergence(self, other):
        p = self._probs()
        lp = nn_layers.log(p + 1e-12)
        lq = nn_layers.log(other._probs() + 1e-12)
        return nn_layers.reduce_sum(p * (lp - lq), dim=-1)


class MultivariateNormalDiag(Distribution):
    """Diagonal-covariance multivariate normal (reference:
    distributions.py MultivariateNormalDiag; loc [d], scale diag [d,d])."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)  # diagonal matrix [d, d]

    def _diag(self):
        d = int(self.scale.shape[-1])
        eye = tensor_layers.assign(np.eye(d, dtype="float32"))
        return nn_layers.reduce_sum(self.scale * eye, dim=-1)

    def entropy(self):
        d = int(self.scale.shape[-1])
        diag = self._diag()
        logdet = nn_layers.reduce_sum(nn_layers.log(diag + 1e-12))
        return 0.5 * d * (1.0 + math.log(2.0 * math.pi)) + logdet

    def kl_divergence(self, other):
        d1 = self._diag()
        d2 = other._diag()
        var1 = d1 * d1
        var2 = d2 * d2
        t = nn_layers.reduce_sum(var1 / var2
                                     + (self.loc - other.loc)
                                     * (self.loc - other.loc) / var2,
                                     dim=-1)
        k = int(self.scale.shape[-1])
        logdet = nn_layers.reduce_sum(
            nn_layers.log(var2 + 1e-12) - nn_layers.log(var1 + 1e-12))
        return 0.5 * (t - k + logdet)
