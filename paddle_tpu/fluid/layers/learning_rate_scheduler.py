"""LR schedulers (reference:
`python/paddle/fluid/layers/learning_rate_scheduler.py`).

Design: the schedule is computed from a persistable `@LR_DECAY_COUNTER@`
step variable with ordinary ops inside the main program, so the whole train
step (decay included) stays one fused XLA computation.
"""
from __future__ import annotations

import math

from .. import framework
from ..layer_helper import LayerHelper, apply_op
from ..initializer import ConstantInitializer
from . import tensor

__all__ = [
    "noam_decay", "exponential_decay", "natural_exp_decay", "inverse_time_decay",
    "polynomial_decay", "piecewise_decay", "cosine_decay", "linear_lr_warmup",
]

LR_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def _decay_step_counter(begin=0):
    helper = LayerHelper("global_step_counter")
    counter = helper.create_global_variable(
        name=LR_COUNTER_NAME, shape=[1], dtype="int64", persistable=True)
    helper.set_variable_initializer(counter, ConstantInitializer(begin))
    helper.main_program.global_block()._prepend_op(
        type="increment", inputs={"X": [counter]},
        outputs={"Out": [counter]}, attrs={"step": 1.0})
    out = tensor.cast(counter, "float32")
    return out


def _single(op_type, ins, attrs, dtype="float32"):
    return apply_op(op_type, op_type, ins, attrs, ["Out"],
                    out_dtype=dtype)[0]


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    step = _decay_step_counter(1)
    a = step ** -0.5
    b = step * float(warmup_steps) ** -1.5
    from . import nn

    lr = learning_rate * (d_model ** -0.5) * nn.elementwise_min(a, b)
    return lr


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _decay_step_counter()
    ratio = step / float(decay_steps)
    if staircase:
        ratio = _single("floor", {"X": [ratio]}, {})
    return tensor.scale(_power(decay_rate, ratio), learning_rate, 0.0)


def _power(base, exponent_var):
    # base^x = exp(x * ln(base))
    from . import nn

    return nn.exp(tensor.scale(exponent_var, math.log(base), 0.0))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _decay_step_counter()
    ratio = step / float(decay_steps)
    if staircase:
        ratio = _single("floor", {"X": [ratio]}, {})
    from . import nn

    return tensor.scale(nn.exp(tensor.scale(ratio, -decay_rate, 0.0)),
                        learning_rate, 0.0)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    step = _decay_step_counter()
    ratio = step / float(decay_steps)
    if staircase:
        ratio = _single("floor", {"X": [ratio]}, {})
    denom = tensor.scale(ratio, decay_rate, 1.0)
    lr = tensor.fill_constant([1], "float32", learning_rate)
    return lr / denom


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _decay_step_counter()
    from . import nn

    capped = nn.elementwise_min(
        step, tensor.fill_constant([1], "float32", float(decay_steps)))
    frac = tensor.scale(capped, 1.0 / float(decay_steps), 0.0)
    one_minus = tensor.scale(frac, -1.0, 1.0)
    powed = _single("elementwise_pow",
                    {"X": [one_minus],
                     "Y": [tensor.fill_constant([1], "float32", power)]},
                    {"axis": -1})
    return tensor.scale(powed, learning_rate - end_learning_rate,
                        end_learning_rate)


def piecewise_decay(boundaries, values):
    step = _decay_step_counter()
    from . import nn

    lr = tensor.fill_constant([1], "float32", values[-1])
    # build nested where() from the last boundary backwards
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        cond = _single("less_than",
                       {"X": [step],
                        "Y": [tensor.fill_constant([1], "float32",
                                                   float(b))]},
                       {}, dtype="bool")
        lr = nn.where(cond, tensor.fill_constant([1], "float32", v), lr)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _decay_step_counter()
    from . import nn

    epoch_f = _single("floor", {"X": [tensor.scale(
        step, 1.0 / step_each_epoch, 0.0)]}, {})
    frac = tensor.scale(epoch_f, math.pi / epochs, 0.0)
    return tensor.scale(nn.cos(frac), learning_rate * 0.5,
                        0.0) + learning_rate * 0.5


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _decay_step_counter()
    from . import nn

    warm = tensor.scale(step, (end_lr - start_lr) / float(warmup_steps),
                        start_lr)
    if not isinstance(learning_rate, framework.Variable):
        learning_rate = tensor.fill_constant([1], "float32",
                                             float(learning_rate))
    cond = _single("less_than",
                   {"X": [step],
                    "Y": [tensor.fill_constant([1], "float32",
                                               float(warmup_steps))]},
                   {}, dtype="bool")
    return nn.where(cond, warm, learning_rate)
