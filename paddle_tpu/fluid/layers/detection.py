"""fluid.layers detection builders (reference:
`python/paddle/fluid/layers/detection.py`) — wrappers over the
detection op families plus the composed losses (`ssd_loss`,
`detection_output`) that the reference implements as python-side op
compositions."""
from __future__ import annotations

from ..layer_helper import apply_op
from . import nn as _nn
from . import tensor as _tensor

__all__ = [
    "prior_box", "density_prior_box", "anchor_generator", "box_coder",
    "yolo_box", "yolov3_loss", "iou_similarity", "box_clip",
    "multiclass_nms", "bipartite_match", "target_assign", "ssd_loss",
    "detection_output", "roi_align", "roi_pool", "prroi_pool",
    "psroi_pool", "rpn_target_assign", "generate_proposals",
    "distribute_fpn_proposals", "collect_fpn_proposals",
    "retinanet_detection_output", "retinanet_target_assign",
    "generate_proposal_labels", "polygon_box_transform",
    "roi_perspective_transform", "deformable_roi_pooling",
    "sigmoid_focal_loss", "box_decoder_and_assign",
    "multiclass_nms2", "locality_aware_nms", "matrix_nms",
    "detection_map", "generate_mask_labels",
]


def _one(op, inputs, attrs, slot="Out", dtype=None):
    return apply_op(op, op, inputs, attrs, [slot], out_dtype=dtype)[0]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, steps=None,
              offset=0.5, name=None):
    outs = apply_op("prior_box", "prior_box",
                    {"Input": [input], "Image": [image]},
                    {"min_sizes": list(min_sizes),
                     "max_sizes": list(max_sizes or []),
                     "aspect_ratios": list(aspect_ratios or [1.0]),
                     "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
                     "flip": flip, "clip": clip,
                     "step_w": (steps or [0, 0])[0],
                     "step_h": (steps or [0, 0])[1], "offset": offset},
                    ["Boxes", "Variances"])
    return outs[0], outs[1]


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=None, clip=False,
                      steps=None, offset=0.5, flatten_to_2d=False,
                      name=None):
    outs = apply_op("density_prior_box", "density_prior_box",
                    {"Input": [input], "Image": [image]},
                    {"densities": list(densities or []),
                     "fixed_sizes": list(fixed_sizes or []),
                     "fixed_ratios": list(fixed_ratios or []),
                     "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
                     "clip": clip, "flatten_to_2d": flatten_to_2d,
                     "step_w": (steps or [0, 0])[0],
                     "step_h": (steps or [0, 0])[1], "offset": offset},
                    ["Boxes", "Variances"])
    return outs[0], outs[1]


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=None, stride=None, offset=0.5, name=None):
    outs = apply_op("anchor_generator", "anchor_generator",
                    {"Input": [input]},
                    {"anchor_sizes": list(anchor_sizes or [64, 128]),
                     "aspect_ratios": list(aspect_ratios or [1.0]),
                     "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
                     "stride": list(stride or [16.0, 16.0]),
                     "offset": offset}, ["Anchors", "Variances"])
    return outs[0], outs[1]


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    ins = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None and not isinstance(
            prior_box_var, (list, tuple)):
        ins["PriorBoxVar"] = [prior_box_var]
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    if isinstance(prior_box_var, (list, tuple)):
        attrs["variance"] = list(prior_box_var)
    return _one("box_coder", ins, attrs, "OutputBox")


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, name=None):
    outs = apply_op("yolo_box", "yolo_box",
                    {"X": [x], "ImgSize": [img_size]},
                    {"anchors": list(anchors), "class_num": class_num,
                     "conf_thresh": conf_thresh,
                     "downsample_ratio": downsample_ratio},
                    ["Boxes", "Scores"])
    return outs[0], outs[1]


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    ins = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        ins["GTScore"] = [gt_score]
    return apply_op("yolov3_loss", "yolov3_loss", ins,
                    {"anchors": list(anchors),
                     "anchor_mask": list(anchor_mask),
                     "class_num": class_num,
                     "ignore_thresh": ignore_thresh,
                     "downsample_ratio": downsample_ratio,
                     "use_label_smooth": use_label_smooth},
                    ["Loss", "ObjectnessMask", "GTMatchMask"])[0]


def iou_similarity(x, y, name=None):
    return _one("iou_similarity", {"X": [x], "Y": [y]}, {})


def box_clip(input, im_info, name=None):
    return _one("box_clip", {"Input": [input], "ImInfo": [im_info]}, {},
                "Output")


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    return _one("multiclass_nms", {"BBoxes": [bboxes], "Scores": [scores]},
                {"score_threshold": score_threshold,
                 "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                 "nms_threshold": nms_threshold, "normalized": normalized,
                 "nms_eta": nms_eta,
                 "background_label": background_label})


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    outs = apply_op("bipartite_match", "bipartite_match",
                    {"DistMat": [dist_matrix]},
                    {"match_type": match_type or "bipartite",
                     "dist_threshold": dist_threshold or 0.5},
                    ["ColToRowMatchIndices", "ColToRowMatchDist"])
    return outs[0], outs[1]


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    outs = apply_op("target_assign", "target_assign",
                    {"X": [input], "MatchIndices": [matched_indices]},
                    {"mismatch_value": mismatch_value or 0},
                    ["Out", "OutWeight"])
    return outs[0], outs[1]


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True,
             sample_size=None):
    """Composed SSD loss (reference layers/detection.py ssd_loss):
    match priors to gt (IoU bipartite), encode box targets, smooth-L1
    localization loss on matched priors + softmax conf loss; negative
    mining is approximated by weighting all unmatched priors with the
    background class (hard mining is data-dependent selection)."""
    from . import loss as _loss

    iou = iou_similarity(gt_box, prior_box)
    matched, _ = bipartite_match(iou, match_type, neg_overlap)
    # localization targets: box_coder encode gives [gt, priors, 4];
    # target_assign picks row match[j] at column j -> [1, priors, 4]
    loc_tgt, loc_w = target_assign(
        box_coder(prior_box, prior_box_var, gt_box), matched,
        mismatch_value=0)
    loc_l = _nn.reduce_sum(
        _nn.elementwise_mul(
            apply_op("huber_loss", "huber_loss",
                     {"X": [location], "Y": [loc_tgt]},
                     {"delta": 1.0}, ["Out"])[0],
            loc_w), dim=-1)
    # conf targets: matched gt label else background
    cls_tgt, cls_w = target_assign(gt_label, matched,
                                   mismatch_value=background_label)
    conf_l = _loss.softmax_with_cross_entropy(
        confidence, _tensor.cast(cls_tgt, "int64"))
    total = _nn.elementwise_add(
        _tensor.scale(_nn.reduce_sum(loc_l, dim=-1),
                      scale=loc_loss_weight),
        _tensor.scale(_nn.reduce_sum(
            _nn.reduce_sum(conf_l, dim=-1), dim=-1),
            scale=conf_loss_weight))
    if normalize:
        denom = _nn.reduce_sum(loc_w)
        total = _nn.elementwise_div(
            total, _nn.elementwise_add(
                denom, _tensor.fill_constant([1], "float32", 1e-6)))
    return total


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode + class-wise NMS (reference layers/detection.py
    detection_output = box_coder(decode) + multiclass_nms)."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    return multiclass_nms(decoded, scores, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold=nms_threshold,
                          nms_eta=nms_eta,
                          background_label=background_label)


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None,
              rois_num=None):
    ins = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    return _one("roi_align", ins,
                {"pooled_height": pooled_height,
                 "pooled_width": pooled_width,
                 "spatial_scale": spatial_scale,
                 "sampling_ratio": sampling_ratio})


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    return _one("roi_pool", ins,
                {"pooled_height": pooled_height,
                 "pooled_width": pooled_width,
                 "spatial_scale": spatial_scale})


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if batch_roi_nums is not None:
        ins["BatchRoINums"] = [batch_roi_nums]
    return _one("prroi_pool", ins,
                {"pooled_height": pooled_height,
                 "pooled_width": pooled_width,
                 "spatial_scale": spatial_scale})


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    return _one("psroi_pool", {"X": [input], "ROIs": [rois]},
                {"output_channels": output_channels,
                 "spatial_scale": spatial_scale,
                 "pooled_height": pooled_height,
                 "pooled_width": pooled_width})


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    outs = apply_op("rpn_target_assign", "rpn_target_assign",
                    {"Anchor": [anchor_box], "GtBoxes": [gt_boxes]},
                    {"rpn_batch_size_per_im": rpn_batch_size_per_im,
                     "rpn_fg_fraction": rpn_fg_fraction,
                     "rpn_positive_overlap": rpn_positive_overlap,
                     "rpn_negative_overlap": rpn_negative_overlap},
                    ["LocationIndex", "ScoreIndex", "TargetLabel",
                     "TargetBBox", "BBoxInsideWeight"])
    from .nn import gather
    pred_loc = gather(bbox_pred, outs[0])
    pred_score = gather(cls_logits, outs[1])
    return pred_score, pred_loc, outs[2], outs[3], outs[4]


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=False, name=None):
    outs = apply_op("generate_proposals", "generate_proposals",
                    {"Scores": [scores], "BboxDeltas": [bbox_deltas],
                     "ImInfo": [im_info], "Anchors": [anchors],
                     "Variances": [variances]},
                    {"pre_nms_topN": pre_nms_top_n,
                     "post_nms_topN": post_nms_top_n,
                     "nms_thresh": nms_thresh, "min_size": min_size,
                     "eta": eta},
                    ["RpnRois", "RpnRoiProbs", "RpnRoisNum"])
    if return_rois_num:
        return outs[0], outs[1], outs[2]
    return outs[0], outs[1]


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    n_levels = max_level - min_level + 1
    outs = apply_op("distribute_fpn_proposals",
                    "distribute_fpn_proposals", {"FpnRois": [fpn_rois]},
                    {"min_level": min_level, "max_level": max_level,
                     "refer_level": refer_level,
                     "refer_scale": refer_scale},
                    {"MultiFpnRois": n_levels, "RestoreIndex": 1})
    return outs[:n_levels], outs[n_levels]


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    return _one("collect_fpn_proposals",
                {"MultiLevelRois": list(multi_rois),
                 "MultiLevelScores": list(multi_scores)},
                {"post_nms_topN": post_nms_top_n}, "FpnRois")


def retinanet_detection_output(bboxes, scores, anchors, im_info=None,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    return _one("retinanet_detection_output",
                {"BBoxes": list(bboxes), "Scores": list(scores),
                 "Anchors": list(anchors)},
                {"score_threshold": score_threshold,
                 "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                 "nms_threshold": nms_threshold, "nms_eta": nms_eta})


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None,
                            im_info=None, num_classes=1,
                            positive_overlap=0.5, negative_overlap=0.4):
    outs = apply_op("retinanet_target_assign", "retinanet_target_assign",
                    {"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
                     "GtLabels": [gt_labels]},
                    {"positive_overlap": positive_overlap,
                     "negative_overlap": negative_overlap},
                    ["LocationIndex", "ScoreIndex", "TargetLabel",
                     "TargetBBox", "BBoxInsideWeight",
                     "ForegroundNumber"])
    from .nn import gather
    pred_loc = gather(bbox_pred, outs[0])
    pred_score = gather(cls_logits, outs[1])
    return (pred_score, pred_loc, outs[2], outs[3], outs[4], outs[5])


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info=None, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=None, class_nums=None,
                             use_random=True, is_cls_agnostic=False,
                             is_cascade_rcnn=False):
    outs = apply_op("generate_proposal_labels",
                    "generate_proposal_labels",
                    {"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
                     "GtBoxes": [gt_boxes]},
                    {"batch_size_per_im": batch_size_per_im,
                     "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
                     "bg_thresh_hi": bg_thresh_hi,
                     "bg_thresh_lo": bg_thresh_lo,
                     "class_nums": class_nums or 81},
                    ["Rois", "LabelsInt32", "BboxTargets",
                     "BboxInsideWeights", "BboxOutsideWeights"])
    return tuple(outs)


def polygon_box_transform(input, name=None):
    return _one("polygon_box_transform", {"Input": [input]}, {},
                "Output")


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    return _one("roi_perspective_transform",
                {"X": [input], "ROIs": [rois]},
                {"transformed_height": transformed_height,
                 "transformed_width": transformed_width,
                 "spatial_scale": spatial_scale})


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1,
                           position_sensitive=False, name=None):
    ins = {"Input": [input], "ROIs": [rois]}
    if not no_trans:
        ins["Trans"] = [trans]
    return apply_op("deformable_psroi_pooling", "deformable_psroi_pooling",
                    ins,
                    {"pooled_height": pooled_height,
                     "pooled_width": pooled_width,
                     "output_dim": input.shape[1]
                     if not position_sensitive else
                     input.shape[1] // (pooled_height * pooled_width),
                     "spatial_scale": spatial_scale,
                     "trans_std": trans_std,
                     "sample_per_part": sample_per_part},
                    ["Output", "TopCount"])[0]


def sigmoid_focal_loss(x, label, fg_num=None, gamma=2.0, alpha=0.25):
    ins = {"X": [x], "Label": [label]}
    if fg_num is not None:
        ins["FgNum"] = [fg_num]
    return _one("sigmoid_focal_loss", ins,
                {"gamma": gamma, "alpha": alpha})


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip_val=4.135, name=None):
    """Reference box_decoder_and_assign_op.cc: decode the per-class box
    deltas [N, C*4], then assign each row the slice of its highest-
    scoring class."""
    outs = apply_op("box_decoder_and_assign", "box_decoder_and_assign",
                    {"PriorBox": [prior_box],
                     "PriorBoxVar": [prior_box_var],
                     "TargetBox": [target_box], "BoxScore": [box_score]},
                    {"box_clip": box_clip_val},
                    ["DecodeBox", "OutputAssignBox"])
    return outs[0], outs[1]


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k,
                    keep_top_k, nms_threshold=0.3, normalized=True,
                    nms_eta=1.0, background_label=0, return_index=False,
                    name=None):
    """multiclass_nms with kept-box indices (reference:
    layers/detection.py multiclass_nms2 / MultiClassNMS2 op)."""
    outs = apply_op("multiclass_nms2", "multiclass_nms2",
                    {"BBoxes": [bboxes], "Scores": [scores]},
                    {"score_threshold": score_threshold,
                     "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                     "nms_threshold": nms_threshold,
                     "normalized": normalized, "nms_eta": nms_eta,
                     "background_label": background_label},
                    ["Out", "Index"])
    return (outs[0], outs[1]) if return_index else outs[0]


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """EAST-style merge-then-suppress NMS (reference:
    layers/detection.py:3397 / locality_aware_nms_op.cc)."""
    return _one("locality_aware_nms",
                {"BBoxes": [bboxes], "Scores": [scores]},
                {"score_threshold": score_threshold,
                 "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                 "nms_threshold": nms_threshold,
                 "normalized": normalized, "nms_eta": nms_eta,
                 "background_label": background_label})


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=False, name=None):
    """Soft decay NMS (reference: layers/detection.py:3527 /
    matrix_nms_op.cc)."""
    outs = apply_op("matrix_nms", "matrix_nms",
                    {"BBoxes": [bboxes], "Scores": [scores]},
                    {"score_threshold": score_threshold,
                     "post_threshold": post_threshold,
                     "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                     "use_gaussian": use_gaussian,
                     "gaussian_sigma": gaussian_sigma,
                     "background_label": background_label,
                     "normalized": normalized},
                    ["Out", "Index", "RoisNum"])
    res = [outs[0]]
    if return_index:
        res.append(outs[1])
    if return_rois_num:
        res.append(outs[2])
    return res[0] if len(res) == 1 else tuple(res)


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None,
                  out_states=None, ap_version="integral"):
    """mAP metric op (reference: layers/detection.py:1223 /
    detection_map_op.h). input_states/out_states follow the reference's
    (pos_count, true_pos, false_pos) triple contract."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("detection_map")
    ins = {"DetectRes": [detect_res], "Label": [label]}
    if has_state is not None:
        ins["HasState"] = [has_state]
    if input_states is not None:
        ins["PosCount"] = [input_states[0]]
        ins["TruePos"] = [input_states[1]]
        ins["FalsePos"] = [input_states[2]]
        # the padded representation carries the reference's per-class
        # LoD of the TruePos/FalsePos state as explicit offset vars
        # (5-tuple states); without them the op cannot attribute state
        # rows to classes
        if len(input_states) >= 5:
            ins["TruePosLod"] = [input_states[3]]
            ins["FalsePosLod"] = [input_states[4]]
    map_out = helper.create_variable_for_type_inference("float32")
    # accumulators go INTO the caller's out_states vars so they can be
    # fed back as next batch's input_states (streaming contract of the
    # reference layer, detection.py:1223). out_states is a 5-tuple:
    # (pos_count, true_pos, false_pos, true_pos_lod, false_pos_lod).
    if out_states is not None and len(out_states) >= 5:
        acc_pc, acc_tp, acc_fp, acc_tpl, acc_fpl = out_states[:5]
    elif out_states is not None:
        raise ValueError(
            "detection_map out_states must carry 5 vars (pos_count, "
            "true_pos, false_pos, true_pos_lod, false_pos_lod): the "
            "per-class lod offsets are part of the streaming state in "
            "the padded representation")
    else:
        acc_pc = helper.create_variable_for_type_inference("int32")
        acc_tp = helper.create_variable_for_type_inference("float32")
        acc_fp = helper.create_variable_for_type_inference("float32")
        acc_tpl = helper.create_variable_for_type_inference("int64")
        acc_fpl = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="detection_map", inputs=ins,
        outputs={"MAP": [map_out], "AccumPosCount": [acc_pc],
                 "AccumTruePos": [acc_tp], "AccumFalsePos": [acc_fp],
                 "AccumTruePosLod": [acc_tpl],
                 "AccumFalsePosLod": [acc_fpl]},
        attrs={"class_num": class_num,
               "background_label": background_label,
               "overlap_threshold": overlap_threshold,
               "evaluate_difficult": evaluate_difficult,
               "ap_type": ap_version})
    return map_out


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         gt_segms_poly_lod=None, gt_segms_point_lod=None):
    """Mask R-CNN mask targets (reference: layers/detection.py:2737 /
    generate_mask_labels_op.cc). The two *_lod inputs carry the
    polygon nesting offsets in the padded representation."""
    ins = {"ImInfo": [im_info], "GtClasses": [gt_classes],
           "IsCrowd": [is_crowd], "GtSegms": [gt_segms],
           "Rois": [rois], "LabelsInt32": [labels_int32]}
    if gt_segms_poly_lod is not None:
        ins["GtSegmsPolyLod"] = [gt_segms_poly_lod]
    if gt_segms_point_lod is not None:
        ins["GtSegmsPointLod"] = [gt_segms_point_lod]
    outs = apply_op("generate_mask_labels", "generate_mask_labels", ins,
                    {"num_classes": num_classes,
                     "resolution": resolution},
                    ["MaskRois", "RoiHasMaskInt32", "MaskInt32"])
    return outs[0], outs[1], outs[2]
