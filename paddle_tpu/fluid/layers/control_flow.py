"""Control-flow layers: While / while_loop / cond / case / switch_case.

Reference: `python/paddle/fluid/layers/control_flow.py` (While:1020, cond,
case, switch_case) over the C++ control-flow ops
(`operators/controlflow/while_op.cc:42`,
`operators/controlflow/conditional_block_op.cc`).

TPU-native: sub-blocks lower to `lax.while_loop` / `lax.cond` /
`lax.switch` with an explicit functional carry (SURVEY.md §7 hard part
(b)): the reference's scope-mutation loop model becomes "carry = the
sub-block's writes that pre-exist in the enclosing env". Loop-carried
values must keep static shape/dtype across iterations — the XLA contract.
Loop bodies run under the same op registry, so everything composes
(collectives inside a while, AMP casts, etc.).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from .. import framework
from ..framework import Variable, unique_name
from ..layer_helper import LayerHelper
from . import tensor as tensor_layers


def _flatten(x):
    if isinstance(x, (list, tuple)):
        out = []
        for e in x:
            out.extend(_flatten(e))
        return out
    return [x]


def _pack_like(template, flat):
    it = iter(flat)

    def rec(t):
        if isinstance(t, (list, tuple)):
            return type(t)(rec(e) for e in t)
        return next(it)

    return rec(template)


# ---------------------------------------------------------------------------
# Scan (fixed-trip lax.scan loop over stacked leading-axis inputs)
# ---------------------------------------------------------------------------

class Scan:
    """Fixed-trip loop lowered to `jax.lax.scan` — the TPU-native way to
    build deep stacks of identical layers: the body is traced and
    XLA-compiled ONCE regardless of trip count (a 12-layer encoder puts
    ONE body in the HLO instead of 12 clones; ~10x smaller program,
    proportionally faster compiles), and reverse-mode grads flow through
    jax.vjp over the scan.

    No direct reference counterpart: the reference's recurrent_op
    (`operators/recurrent_op.cc`) steps a sub-block per timestep via
    scope mutation and needs a dedicated recurrent_grad; here the loop
    is functional so autodiff is ordinary vjp. Carry contract is the
    While contract (`while_op.cc:42` analogue): loop-carried vars are
    created+initialized BEFORE the loop and rebound inside the body
    (e.g. ``layers.assign(new_x, output=x)``); per-layer parameters are
    stacked on a leading [n, ...] axis and sliced with
    ``scan.slice_input(stacked)`` inside the body.

    remat=True wraps the body in ``jax.checkpoint``: per-iteration
    activation recompute (the scan-over-layers equivalent of
    RecomputeOptimizer's checkpoint segments) — memory O(n * boundary)
    instead of O(n * body-internals).

    Usage::

        scan = layers.Scan(n=num_layers)
        with scan.block():
            w = scan.slice_input(stacked_w)   # [n, H, H] -> [H, H]
            new_x = layers.matmul(x, w)
            layers.assign(new_x, output=x)    # rebind the carry
    """

    def __init__(self, n: int, remat: bool = False, name: Optional[str] = None):
        if int(n) < 1:
            raise ValueError("Scan needs n >= 1, got %r" % (n,))
        self.n = int(n)
        self.remat = bool(remat)
        self.helper = LayerHelper("scan", name=name)
        self._main = framework.default_main_program()
        self._sub = None
        self._xs_stacked: List[Variable] = []
        self._xs_slice: List[Variable] = []
        self._iter_var: Optional[Variable] = None

    def iteration(self) -> Variable:
        """[1] int32 var holding the current iteration index inside the
        body — e.g. the scatter index for per-iteration slice updates of
        stacked state (BN running stats in a scanned residual stage).
        int32 is JAX's canonical index dtype (int64 would truncate
        under default config and warn on every trace)."""
        if self._sub is None:
            raise ValueError(
                "iteration() must be called inside `with scan.block():`")
        if self._iter_var is None:
            self._iter_var = self._sub.create_var(
                name=unique_name("scan_iter"), shape=(1,), dtype="int32")
        return self._iter_var

    def slice_input(self, stacked: Variable) -> Variable:
        """Declare `stacked` [n, ...] as a per-iteration input; returns
        its [...] slice for use inside the body."""
        if self._sub is None:
            raise ValueError(
                "slice_input must be called inside `with scan.block():`")
        if not isinstance(stacked, Variable):
            raise TypeError("slice_input expects a Variable")
        if int(stacked.shape[0]) != self.n:
            raise ValueError(
                "stacked input %r leading dim %s != scan n %d"
                % (stacked.name, stacked.shape[0], self.n))
        sl = self._sub.create_var(
            name=unique_name("scan_slice"),
            shape=tuple(int(d) for d in stacked.shape[1:]),
            dtype=stacked.dtype)
        self._xs_stacked.append(stacked)
        self._xs_slice.append(sl)
        return sl

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            prog = self._main
            self._sub = prog._create_block()
            self._xs_stacked, self._xs_slice = [], []
            self._iter_var = None
            try:
                yield self
            except BaseException:
                # body raised: leave no half-built scan op behind (the
                # While guard's contract)
                prog._rollback()
                self._sub = None
                raise
            prog._rollback()
            sub = self._sub
            self._sub = None
            parent = prog.current_block()
            parent.append_op(
                type="scan",
                inputs={"X": list(self._xs_stacked)},
                outputs={},
                attrs={"sub_block": sub.idx, "n": self.n,
                       "remat": self.remat,
                       "xs_stacked": [v.name for v in self._xs_stacked],
                       "xs_slice": [v.name for v in self._xs_slice],
                       "iter_var": self._iter_var.name
                       if self._iter_var is not None else ""})

        return ctx()


# ---------------------------------------------------------------------------
# While (1.x context-manager form)
# ---------------------------------------------------------------------------

class While:
    """``while cond_var:`` over a sub-block (reference:
    control_flow.py While / while_op.cc:42).

    All loop-carried vars must be created AND initialized before the loop;
    writes inside the block to pre-existing vars are carried functionally.
    """

    def __init__(self, cond, is_test=False, name=None):
        if not isinstance(cond, Variable):
            raise TypeError("While cond must be a Variable")
        self.cond_var = cond
        self.helper = LayerHelper("while", name=name)
        self._main = framework.default_main_program()

    def block(self):
        return _WhileGuard(self)


class _WhileGuard:
    def __init__(self, while_op: While):
        self._w = while_op

    def __enter__(self):
        prog = self._w._main
        self._sub = prog._create_block()
        return self

    def __exit__(self, exc_type, exc_val, tb):
        prog = self._w._main
        prog._rollback()
        if exc_type is not None:
            return False
        parent = prog.current_block()
        parent.append_op(
            type="while",
            inputs={"Condition": [self._w.cond_var]},
            outputs={},
            attrs={"sub_block": self._sub.idx,
                   "cond_name": self._w.cond_var.name})
        return True


def while_loop(cond: Callable, body: Callable, loop_vars: Sequence,
               is_test: bool = False, name: Optional[str] = None):
    """Functional while (reference: control_flow.py while_loop): runs
    ``body`` while ``cond(*loop_vars)`` holds; returns the final vars."""
    loop_list = list(loop_vars)
    pre_cond = cond(*loop_list)
    w = While(pre_cond, is_test=is_test, name=name)
    with w.block():
        out = body(*loop_list)
        out_list = out if isinstance(out, (list, tuple)) else [out]
        flat_in = _flatten(loop_list)
        flat_out = _flatten(list(out_list))
        if len(flat_in) != len(flat_out):
            raise ValueError(
                "body returned %d vars, expected %d (the loop_vars "
                "structure)" % (len(flat_out), len(flat_in)))
        for lv, ov in zip(flat_in, flat_out):
            if ov is not lv:
                tensor_layers.assign(ov, output=lv)
        new_cond = cond(*loop_list)
        tensor_layers.assign(new_cond, output=pre_cond)
    return loop_vars


# ---------------------------------------------------------------------------
# cond / case / switch_case
# ---------------------------------------------------------------------------

def _trace_branch(prog, fn, out_vars=None):
    """Runs fn inside a fresh sub-block; assigns its returns onto out_vars
    (created in the parent on the first branch). Returns (block_idx,
    out_vars, template)."""
    sub = prog._create_block()
    try:
        ret = fn() if fn is not None else None
    except BaseException:
        prog._rollback()
        raise
    flat = _flatten(ret) if ret is not None else []
    if out_vars is None:
        parent = prog.block(sub.parent_idx)
        out_vars = []
        for i, r in enumerate(flat):
            if not isinstance(r, Variable):
                r = tensor_layers.fill_constant([1], "float32", float(r))
                flat[i] = r
            out_vars.append(parent.create_var(
                name=framework.unique_name("cond_out"),
                shape=r.shape, dtype=r.dtype))
    if len(flat) != len(out_vars):
        prog._rollback()
        raise ValueError("branches must return the same structure "
                         "(%d vs %d leaves)" % (len(flat), len(out_vars)))
    for r, ov in zip(flat, out_vars):
        if not isinstance(r, Variable):
            r = tensor_layers.fill_constant(ov.shape, ov.dtype, float(r))
        tensor_layers.assign(r, output=ov)
    prog._rollback()
    return sub.idx, out_vars, ret


def cond(pred, true_fn: Optional[Callable] = None,
         false_fn: Optional[Callable] = None, name: Optional[str] = None):
    """Two-way branch (reference: control_flow.py cond /
    conditional_block_op.cc). Both branches must return the same
    structure of vars with matching shapes/dtypes."""
    prog = framework.default_main_program()
    t_idx, out_vars, template = _trace_branch(prog, true_fn)
    f_idx, _, _ = _trace_branch(prog, false_fn, out_vars)
    parent = prog.current_block()
    parent.append_op(
        type="cond",
        inputs={"Cond": [pred]},
        outputs={"Out": list(out_vars)},
        attrs={"sub_block_t": t_idx, "sub_block_f": f_idx,
               "out_names": [v.name for v in out_vars],
               "cond_name": pred.name})
    if template is None:
        return None
    if isinstance(template, (list, tuple)):
        return _pack_like(template, out_vars)
    return out_vars[0]


def switch_case(branch_index, branch_fns, default=None,
                name: Optional[str] = None):
    """N-way branch on an integer index (reference: control_flow.py
    switch_case) -> lax.switch."""
    prog = framework.default_main_program()
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and all(isinstance(f, (list, tuple)) and len(f) == 2
                            for f in branch_fns):
        # reference API also accepts a list of (index, callable) pairs
        items = sorted((int(k), f) for k, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    keys = [int(k) for k, _ in items]
    fns = [f for _, f in items]
    if default is None:
        # promote the last branch to default (and drop it from the match
        # list so it isn't traced twice)
        default = fns.pop()
        keys.pop()

    out_vars = None
    blocks = []
    template = None
    for f in fns:
        idx, out_vars, tmpl = _trace_branch(prog, f, out_vars)
        template = template if template is not None else tmpl
        blocks.append(idx)
    d_idx, out_vars, _ = _trace_branch(prog, default, out_vars)
    blocks.append(d_idx)

    parent = prog.current_block()
    parent.append_op(
        type="switch_case",
        inputs={"Index": [branch_index]},
        outputs={"Out": list(out_vars)},
        attrs={"sub_blocks": blocks, "keys": keys,
               "out_names": [v.name for v in out_vars],
               "index_name": branch_index.name})
    if isinstance(template, (list, tuple)):
        return _pack_like(template, out_vars)
    return out_vars[0]


def case(pred_fn_pairs, default=None, name: Optional[str] = None):
    """First-match-wins chain of (pred, fn) (reference: control_flow.py
    case), built from nested cond."""
    pairs = list(pred_fn_pairs)
    if not pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]
        if not pairs:
            return default()

    def build(i):
        if i == len(pairs):
            return default
        pred, fn = pairs[i]
        return lambda: cond(pred, fn, build(i + 1))

    return build(0)()


# ---------------------------------------------------------------------------
# misc control-flow helpers the reference exposes alongside While
# ---------------------------------------------------------------------------

def increment(x, value=1.0, in_place=True):
    """Reference: control_flow.py increment — x += value, in place by
    rebinding the same var name."""
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(
        dtype=x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def less_than(x, y, force_cpu=None, cond=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def _compare(op_type, x, y, out):
    helper = LayerHelper(op_type)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Host-side tensor print passthrough (reference: control_flow.py
    Print -> print_op). Returns its input so it can be chained."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={"message": message or "", "summarize": summarize,
               "first_n": first_n, "print_phase": print_phase})
    return out


def Assert(cond, data=None, summarize=20, name=None):
    """Runtime assertion op (reference: control_flow.py Assert ->
    assert_op): raises AssertionError when `cond` is not all-true."""
    helper = LayerHelper("assert")
    inputs = {"Cond": [cond]}
    if data:
        inputs["Data"] = list(data)
    helper.append_op(type="assert", inputs=inputs, outputs={},
                     attrs={"summarize": summarize,
                            "message": name or ""})


class StaticRNN:
    """Static-length RNN builder (reference: layers/control_flow.py
    StaticRNN + operators/recurrent_op.cc). The user writes the step
    body ONCE inside `with rnn.step():` over time-major [T, B, ...]
    sequence inputs; the reference executes it via recurrent_op's
    sub-block loop. TPU-native: the step body is captured as an op
    template and UNROLLED at build time by cloning it per timestep with
    name substitution — T is static here by definition (the reference
    requires it too), unrolling gives XLA the whole computation to
    fuse/pipeline, and the backward falls out of the ordinary
    jax.vjp over the flattened program (no recurrent_grad op needed).
    For data-dependent lengths use layers.while_loop / layers.rnn."""

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.seq_len = None
        self._step_inputs = []   # (seq var, t0 var)
        self._mems = []          # {"pre": var, "update": name|None}
        self._step_outputs = []  # t0 output vars
        self._results = None
        self._start_idx = None
        # ops that SEED iteration 0 (t0 slices, memory init fills):
        # they must not be re-cloned per timestep — a clone would remap
        # their output names over the prev-iteration substitutions
        self._seed_op_ids = set()

    # -- step context ------------------------------------------------------
    def step(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self.status = StaticRNN.IN_RNN_BLOCK
            self._start_idx = len(self.helper.main_block.ops)
            try:
                yield
            finally:
                self.status = StaticRNN.AFTER_RNN_BLOCK
                self._complete()

        return ctx()

    def _assert_in_step(self, what):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise ValueError("%s can only be invoked inside rnn.step()"
                             % what)

    def _slice_time(self, seq, t):
        """seq [T, B, ...] -> [B, ...] at time t."""
        block = self.helper.main_block
        sl = block.create_var(
            name=unique_name("srnn_slice"),
            shape=(1,) + tuple(seq.shape[1:]), dtype=seq.dtype)
        block.append_op(type="slice", inputs={"Input": [seq]},
                        outputs={"Out": [sl]},
                        attrs={"axes": [0], "starts": [t],
                               "ends": [t + 1]})
        out = block.create_var(name=unique_name("srnn_x"),
                               shape=tuple(seq.shape[1:]),
                               dtype=seq.dtype)
        block.append_op(type="reshape2", inputs={"X": [sl]},
                        outputs={"Out": [out], "XShape": [block.create_var(
                            name=unique_name("srnn_xs"), shape=(),
                            dtype=seq.dtype)]},
                        attrs={"shape": [int(d) for d in seq.shape[1:]]})
        return out

    def step_input(self, x):
        """Mark x [seq_len, batch, ...] as a sequence input; returns the
        per-step [batch, ...] slice."""
        self._assert_in_step("step_input")
        if self.seq_len is None:
            self.seq_len = int(x.shape[0])
        elif self.seq_len != int(x.shape[0]):
            raise ValueError("Static RNN only takes fixed seq_len: %d vs "
                             "%d" % (self.seq_len, int(x.shape[0])))
        n_before = len(self.helper.main_block.ops)
        t0 = self._slice_time(x, 0)
        for op in self.helper.main_block.ops[n_before:]:
            self._seed_op_ids.add(id(op))
        self._step_inputs.append((x, t0))
        return t0

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1):
        """Loop-carried state: init var, or zeros shaped like `shape`
        with the batch dim taken from batch_ref (reference:
        StaticRNN.memory)."""
        self._assert_in_step("memory")
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError(
                    "memory needs an init var OR shape + batch_ref")
            from . import tensor as t_layers

            n_before = len(self.helper.main_block.ops)
            feat = [int(d) for d in shape if int(d) != -1]
            init = t_layers.fill_constant_batch_size_like(
                batch_ref, shape=[-1] + feat, dtype=batch_ref.dtype,
                value=init_value, input_dim_idx=0, output_dim_idx=0)
            for op in self.helper.main_block.ops[n_before:]:
                self._seed_op_ids.add(id(op))
        self._mems.append({"pre": init, "update": None})
        return init

    def update_memory(self, mem, x):
        self._assert_in_step("update_memory")
        for m in self._mems:
            if m["pre"].name == mem.name:
                m["update"] = x.name
                return
        raise ValueError("update_memory: %r is not a memory of this RNN"
                         % mem.name)

    def step_output(self, o):
        self._assert_in_step("step_output")
        self._step_outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    # -- unrolling ---------------------------------------------------------
    def _complete(self):
        if self.seq_len is None:
            raise ValueError("StaticRNN needs at least one step_input")
        for m in self._mems:
            if m["update"] is None:
                raise ValueError("memory %r has no update_memory"
                                 % m["pre"].name)
        block = self.helper.main_block
        template = [op for op in block.ops[self._start_idx:]
                    if id(op) not in self._seed_op_ids]
        prev = {m["pre"].name: m["update"] for m in self._mems}
        outs_per_t = {o.name: [o.name] for o in self._step_outputs}

        for t in range(1, self.seq_len):
            mapping = {}
            for seq, t0 in self._step_inputs:
                mapping[t0.name] = self._slice_time(seq, t).name
            for m in self._mems:
                mapping[m["pre"].name] = prev[m["pre"].name]
            for op in template:
                if any(k in op.attrs for k in ("sub_block", "blocks")):
                    raise NotImplementedError(
                        "StaticRNN step body must not contain nested "
                        "control-flow blocks")
                ins = {}
                for slot, names in op.input_names.items():
                    ins[slot] = [mapping.get(n, n) for n in names]
                outs = {}
                for slot, names in op.output_names.items():
                    mapped = []
                    for n in names:
                        v = block._find_var_recursive(n)
                        if v is not None and v.persistable:
                            mapped.append(n)  # params update in place
                            continue
                        fresh = unique_name("%s_t%d" % (n, t))
                        nv = block.create_var(
                            name=fresh,
                            shape=v.shape if v is not None else (),
                            dtype=v.dtype if v is not None
                            else "float32")
                        mapping[n] = fresh
                        mapped.append(fresh)
                    outs[slot] = mapped
                block.append_op(type=op.type, inputs=ins, outputs=outs,
                                attrs=dict(op.attrs))
            for m in self._mems:
                prev[m["pre"].name] = mapping.get(m["update"],
                                                  m["update"])
            for o in self._step_outputs:
                outs_per_t[o.name].append(mapping.get(o.name, o.name))

        # stack each step output over time: [T, B, ...]
        results = []
        for o in self._step_outputs:
            out = block.create_var(
                name=unique_name("srnn_out"),
                shape=(self.seq_len,) + tuple(o.shape), dtype=o.dtype)
            block.append_op(type="stack",
                            inputs={"X": outs_per_t[o.name]},
                            outputs={"Y": [out]}, attrs={"axis": 0})
            results.append(out)
        self._results = results

    def __call__(self, *args, **kwargs):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise ValueError("rnn() is only valid after the step block")
        if not self._results:
            raise ValueError("StaticRNN produced no step_output")
        return (self._results[0] if len(self._results) == 1
                else self._results)


def is_empty(x, cond=None):
    """True iff x has zero elements (reference: control_flow.py:3779 /
    is_empty_op.h — always computed host-side there too; here shapes
    are static so it is a trace-time constant)."""
    helper = LayerHelper("is_empty")
    out = cond if cond is not None else \
        helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """Permute batch rows into the rank table's order (reference:
    control_flow.py:3738 / reorder_lod_tensor_by_rank_op.cc)."""
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="reorder_lod_tensor_by_rank",
                     inputs={"X": [x], "RankTable": [rank_table]},
                     outputs={"Out": [out]}, attrs={})
    return out
