"""Loss layers (reference: `python/paddle/fluid/layers/loss.py`)."""
from __future__ import annotations

from ..layer_helper import apply_op

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "square_error_cost", "huber_loss",
    "smooth_l1", "kldiv_loss", "mse_loss",
]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return apply_op("cross_entropy", "cross_entropy",
                    {"X": [input], "Label": [label]},
                    {"soft_label": soft_label, "ignore_index": ignore_index},
                    ["Y"], out_dtype=input.dtype)[0]


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    outs = apply_op("softmax_with_cross_entropy",
                    "softmax_with_cross_entropy",
                    {"Logits": [logits], "Label": [label]},
                    {"soft_label": soft_label, "ignore_index": ignore_index,
                     "axis": axis},
                    ["Softmax", "Loss"], out_dtype=logits.dtype)
    softmax, loss = outs[0], outs[1]
    if return_softmax:
        return loss, softmax
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    return apply_op("sigmoid_cross_entropy_with_logits",
                    "sigmoid_cross_entropy_with_logits",
                    {"X": [x], "Label": [label]},
                    {"ignore_index": ignore_index, "normalize": normalize},
                    ["Out"], out_dtype=x.dtype)[0]


def square_error_cost(input, label):
    return apply_op("square_error_cost", "square_error_cost",
                    {"X": [input], "Y": [label]}, {}, ["Out"],
                    out_dtype=input.dtype)[0]


def mse_loss(input, label):
    from . import nn

    return nn.reduce_mean(square_error_cost(input, label))


def huber_loss(input, label, delta):
    return apply_op("huber_loss", "huber_loss",
                    {"X": [input], "Y": [label]}, {"delta": float(delta)},
                    ["Out", "Residual"], out_dtype=input.dtype)[0]


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    return apply_op("smooth_l1_loss", "smooth_l1_loss",
                    {"X": [x], "Y": [y]}, {"sigma": sigma or 1.0},
                    ["Out", "Diff"], out_dtype=x.dtype)[0]


def kldiv_loss(x, target, reduction="mean", name=None):
    return apply_op("kldiv_loss", "kldiv_loss",
                    {"X": [x], "Target": [target]}, {"reduction": reduction},
                    ["Loss"], out_dtype=x.dtype)[0]
