"""Loss layers (reference: `python/paddle/fluid/layers/loss.py`)."""
from __future__ import annotations

from ..layer_helper import apply_op

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "square_error_cost", "huber_loss",
    "smooth_l1", "kldiv_loss", "mse_loss", "fused_linear_softmax_xent",
]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return apply_op("cross_entropy", "cross_entropy",
                    {"X": [input], "Label": [label]},
                    {"soft_label": soft_label, "ignore_index": ignore_index},
                    ["Y"], out_dtype=input.dtype)[0]


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    outs = apply_op("softmax_with_cross_entropy",
                    "softmax_with_cross_entropy",
                    {"Logits": [logits], "Label": [label]},
                    {"soft_label": soft_label, "ignore_index": ignore_index,
                     "axis": axis},
                    ["Softmax", "Loss"], out_dtype=logits.dtype)
    softmax, loss = outs[0], outs[1]
    if return_softmax:
        return loss, softmax
    return loss


def fused_linear_softmax_xent(input, label, size, param_attr=None,
                              bias_attr=None, chunk_size=8192, name=None):
    """Classifier projection fused with softmax cross-entropy: creates the
    [H, size] weight (+ optional [size] bias) and returns the per-example
    loss [..., 1] WITHOUT materializing [N, size] logits (streamed vocab
    chunks — see ops/fused_ops.py fused_linear_softmax_xent). Use for
    large-vocab heads (masked-LM, LM output); for small heads the unfused
    fc + softmax_with_cross_entropy is equivalent."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("fused_linear_softmax_xent", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    in_dim = int(input.shape[-1])
    w = helper.create_parameter(helper.param_attr, shape=[in_dim, size],
                                dtype=input.dtype)
    inputs = {"X": [input], "W": [w], "Label": [label]}
    if helper.bias_attr is not False and helper.bias_attr is not None:
        b = helper.create_parameter(helper.bias_attr, shape=[size],
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    return apply_op(helper, "fused_linear_softmax_xent", inputs,
                    {"chunk_size": int(chunk_size)}, ["Loss"],
                    out_dtype="float32")[0]


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    return apply_op("sigmoid_cross_entropy_with_logits",
                    "sigmoid_cross_entropy_with_logits",
                    {"X": [x], "Label": [label]},
                    {"ignore_index": ignore_index, "normalize": normalize},
                    ["Out"], out_dtype=x.dtype)[0]


def square_error_cost(input, label):
    return apply_op("square_error_cost", "square_error_cost",
                    {"X": [input], "Y": [label]}, {}, ["Out"],
                    out_dtype=input.dtype)[0]


def mse_loss(input, label):
    from . import nn

    return nn.reduce_mean(square_error_cost(input, label))


def huber_loss(input, label, delta):
    return apply_op("huber_loss", "huber_loss",
                    {"X": [input], "Y": [label]}, {"delta": float(delta)},
                    ["Out", "Residual"], out_dtype=input.dtype)[0]


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    return apply_op("smooth_l1_loss", "smooth_l1_loss",
                    {"X": [x], "Y": [y]}, {"sigma": sigma or 1.0},
                    ["Out", "Diff"], out_dtype=x.dtype)[0]


def kldiv_loss(x, target, reduction="mean", name=None):
    return apply_op("kldiv_loss", "kldiv_loss",
                    {"X": [x], "Target": [target]}, {"reduction": reduction},
                    ["Loss"], out_dtype=x.dtype)[0]
