"""Operator-overload sugar for Variable (reference:
`python/paddle/fluid/layers/math_op_patch.py`)."""
from __future__ import annotations

import numpy as np


def binary(x, other, op_type, reverse=False):
    from . import tensor as t
    from ..layer_helper import apply_op

    if np.isscalar(other):
        if op_type == "elementwise_add":
            return t.scale(x, 1.0, float(other))
        if op_type == "elementwise_sub" and not reverse:
            return t.scale(x, 1.0, -float(other))
        if op_type == "elementwise_sub" and reverse:
            return t.scale(x, -1.0, float(other))
        if op_type == "elementwise_mul":
            return t.scale(x, float(other), 0.0)
        if op_type == "elementwise_div" and not reverse:
            return t.scale(x, 1.0 / float(other), 0.0)
        other = t.fill_constant([1], x.dtype, float(other))
    a, b = (other, x) if reverse else (x, other)
    return apply_op(op_type, op_type, {"X": [a], "Y": [b]}, {"axis": -1},
                    ["Out"], out_dtype=x.dtype)[0]
