"""layers.* op wrappers (reference: `python/paddle/fluid/layers/nn.py`, 15k
LoC of ~300 builders). Each builder creates params via LayerHelper (init ops
go to the startup program) and appends its compute op; in dygraph mode the
same builders execute eagerly."""
from __future__ import annotations

import numpy as np

from .. import framework
from ..framework import Variable, in_dygraph_mode
from ..layer_helper import LayerHelper, apply_op
from ..initializer import ConstantInitializer, NormalInitializer
from ...core.types import normalize_dtype

__all__ = [
    "fc", "embedding", "conv2d", "conv2d_transpose", "pool2d", "batch_norm",
    "layer_norm", "group_norm", "instance_norm", "dropout", "relu",
    "sigmoid", "tanh", "sqrt", "square", "exp", "log", "abs", "ceil",
    "floor", "round", "reciprocal", "gelu", "leaky_relu", "elu", "relu6",
    "softplus", "softsign", "swish", "hard_sigmoid", "hard_swish", "prelu",
    "softmax", "log_softmax", "matmul", "mul", "elementwise_add",
    "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_max", "elementwise_min", "elementwise_pow",
    "elementwise_mod", "elementwise_floordiv", "reduce_sum",
    "reduce_mean", "reduce_max", "reduce_min", "reduce_prod", "reduce_all",
    "reduce_any", "mean", "accuracy", "topk", "one_hot", "clip",
    "clip_by_norm", "l2_normalize", "label_smooth", "pad", "pad2d",
    "unsqueeze", "squeeze", "stack", "unstack", "expand", "expand_as",
    "gather", "gather_nd", "scatter", "slice", "strided_slice", "split",
    "where", "cond_not_supported", "sequence_pool", "sequence_softmax",
    "sequence_mask", "sequence_expand", "sequence_reshape",
    "sequence_reverse", "image_resize", "resize_nearest", "flatten",
    "logsigmoid", "erf", "sin", "cos", "maximum", "minimum",
    "scaled_dot_product_attention",
]


def _single(op_type, inputs, attrs, dtype=None, helper=None):
    return apply_op(helper or op_type, op_type, inputs, attrs, ["Out"],
                    out_dtype=dtype)[0]


# ---------------------------------------------------------------------------
# parametric layers
# ---------------------------------------------------------------------------

def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Fully-connected (reference: layers/nn.py fc) = mul + bias + act."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    # one weight PER input; a named param_attr names only the first and
    # the copies auto-name (reference: LayerHelper.multiple_param_attr —
    # reusing the name would silently alias every input's weight)
    param_attrs = helper.multiple_param_attr(len(inputs))
    if not isinstance(param_attrs, (list, tuple)):
        param_attrs = [param_attrs] * len(inputs)
    mul_results = []
    for inp, w_attr in zip(inputs, param_attrs):
        in_dim = int(np.prod(inp.shape[num_flatten_dims:]))
        w = helper.create_parameter(
            w_attr, shape=[in_dim, size], dtype=inp.dtype)
        out = _single("mul", {"X": [inp], "Y": [w]},
                      {"x_num_col_dims": num_flatten_dims,
                       "y_num_col_dims": 1}, dtype=inp.dtype, helper=helper)
        mul_results.append(out)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = _single("sum", {"X": mul_results}, {},
                           dtype=mul_results[0].dtype, helper=helper)
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(helper.param_attr, shape=list(size),
                                dtype=dtype)
    pad = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    return _single("lookup_table", {"W": [w], "Ids": [input]},
                   {"padding_idx": pad, "is_sparse": is_sparse,
                    "is_distributed": is_distributed},
                   dtype=dtype, helper=helper)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    """param_attr may be a Variable: convolve with that EXISTING filter
    instead of creating a parameter — the scan-over-blocks path passes
    per-iteration slices of stacked [L, out, in, kh, kw] filters
    (layers.Scan)."""
    helper = LayerHelper("conv2d",
                         param_attr=None if isinstance(param_attr, Variable)
                         else param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    std = (2.0 / fan_in) ** 0.5
    if isinstance(param_attr, Variable):
        if tuple(int(d) for d in param_attr.shape) != tuple(filter_shape):
            raise ValueError(
                "conv2d: provided filter var %r has shape %s, expected "
                "%s (pass the per-iteration slice, not the stack)"
                % (param_attr.name, tuple(param_attr.shape),
                   tuple(filter_shape)))
        w = param_attr
    else:
        w = helper.create_parameter(
            helper.param_attr, shape=filter_shape, dtype=input.dtype,
            default_initializer=NormalInitializer(0.0, std))
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = ([dilation, dilation] if isinstance(dilation, int)
                else list(dilation))
    pre_bias = apply_op(helper, "conv2d",
                        {"Input": [input], "Filter": [w]},
                        {"strides": stride, "paddings": padding,
                         "dilations": dilation, "groups": groups},
                        ["Output"], out_dtype=input.dtype)[0]
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=input.dtype)
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    pre_bias = apply_op(helper, "conv2d_transpose",
                        {"Input": [input], "Filter": [w]},
                        {"strides": stride, "paddings": padding,
                         "dilations": [dilation, dilation]
                         if isinstance(dilation, int) else list(dilation),
                         "groups": groups},
                        ["Output"], out_dtype=input.dtype)[0]
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCHW"):
    attrs = {
        "pooling_type": pool_type,
        "ksize": [pool_size, pool_size] if isinstance(pool_size, int)
        else list(pool_size),
        "strides": [pool_stride, pool_stride]
        if isinstance(pool_stride, int) else list(pool_stride),
        "paddings": [pool_padding, pool_padding]
        if isinstance(pool_padding, int) else list(pool_padding),
        "global_pooling": global_pooling,
        "ceil_mode": ceil_mode,
        "exclusive": exclusive,
    }
    return _single("pool2d", {"X": [input]}, attrs, dtype=input.dtype)


def adaptive_pool2d(input, pool_size, pool_type="max", name=None):
    return _single("pool2d", {"X": [input]},
                   {"pooling_type": pool_type, "ksize": list(pool_size),
                    "adaptive": True}, dtype=input.dtype)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    helper = LayerHelper("batch_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    dtype = input.dtype if input.dtype != "float16" else "float32"
    scale = helper.create_parameter(
        helper.param_attr, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(helper.bias_attr, shape=[c], dtype=dtype,
                                   is_bias=True)

    if in_dygraph_mode():
        from ..dygraph import base as dy_base

        mean = dy_base.create_eager_parameter(
            None, [c], dtype, ConstantInitializer(0.0), trainable=False,
            name=moving_mean_name)
        var = dy_base.create_eager_parameter(
            None, [c], dtype, ConstantInitializer(1.0), trainable=False,
            name=moving_variance_name)
        outs = dy_base.trace_op(
            "batch_norm",
            {"X": [input], "Scale": [scale], "Bias": [bias],
             "Mean": [mean], "Variance": [var]},
            {"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
             "data_layout": data_layout,
             "use_global_stats": use_global_stats},
            ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"])
        mean._assign_value(outs[1])
        var._assign_value(outs[2])
        y = outs[0]
        return helper.append_activation(y)

    from ..framework import unique_name

    mean = helper.create_parameter(
        framework_attr_for(moving_mean_name or unique_name(
            helper.name + ".mean")),
        shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(0.0))
    mean.trainable = False
    var = helper.create_parameter(
        framework_attr_for(moving_variance_name or unique_name(
            helper.name + ".var")),
        shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    var.trainable = False

    saved_mean = helper.create_variable_for_type_inference(dtype)
    saved_var = helper.create_variable_for_type_inference(dtype)
    y = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [var]},
        outputs={"Y": [y], "MeanOut": [mean], "VarianceOut": [var],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout,
               "use_global_stats": use_global_stats})
    return helper.append_activation(y)


def framework_attr_for(name):
    from ..param_attr import ParamAttr

    return ParamAttr(name=name, trainable=False)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """scale/shift accept a Variable to normalize with EXISTING affine
    vars instead of creating parameters — the scan-over-layers body
    passes per-iteration slices of stacked [L, H] scale/bias params
    (layers.Scan)."""
    helper = LayerHelper("layer_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if isinstance(scale, Variable):
        inputs["Scale"] = [scale]
    elif scale:
        s = helper.create_parameter(
            helper.param_attr, shape=norm_shape, dtype=input.dtype,
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if isinstance(shift, Variable):
        inputs["Bias"] = [shift]
    elif shift:
        b = helper.create_parameter(helper.bias_attr, shape=norm_shape,
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    outs = apply_op(helper, "layer_norm", inputs,
                    {"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
                    ["Y", "Mean", "Variance"], out_dtype=input.dtype)
    return helper.append_activation(outs[0])


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, name=None):
    helper = LayerHelper("group_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1]
    inputs = {"X": [input]}
    if param_attr is not False:
        inputs["Scale"] = [helper.create_parameter(
            helper.param_attr, shape=[c], dtype=input.dtype,
            default_initializer=ConstantInitializer(1.0))]
    if bias_attr is not False:
        inputs["Bias"] = [helper.create_parameter(
            helper.bias_attr, shape=[c], dtype=input.dtype, is_bias=True)]
    outs = apply_op(helper, "group_norm", inputs,
                    {"groups": groups, "epsilon": epsilon},
                    ["Y", "Mean", "Variance"], out_dtype=input.dtype)
    return helper.append_activation(outs[0])


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    c = input.shape[1]
    inputs = {"X": [input]}
    if param_attr is not False:
        inputs["Scale"] = [helper.create_parameter(
            helper.param_attr, shape=[c], dtype=input.dtype,
            default_initializer=ConstantInitializer(1.0))]
    if bias_attr is not False:
        inputs["Bias"] = [helper.create_parameter(
            helper.bias_attr, shape=[c], dtype=input.dtype, is_bias=True)]
    outs = apply_op(helper, "instance_norm", inputs, {"epsilon": epsilon},
                    ["Y", "SavedMean", "SavedVariance"],
                    out_dtype=input.dtype)
    return outs[0]


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    outs = apply_op("dropout", "dropout", {"X": [x]},
                    {"dropout_prob": dropout_prob, "is_test": is_test,
                     "seed": seed or 0,
                     "dropout_implementation": dropout_implementation},
                    ["Out", "Mask"], out_dtype=x.dtype)
    return outs[0]


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        helper.param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25))
    return apply_op(helper, "prelu", {"X": [x], "Alpha": [alpha]},
                    {"mode": mode}, ["Out"], out_dtype=x.dtype)[0]


# ---------------------------------------------------------------------------
# functional (no params)
# ---------------------------------------------------------------------------

def _make_act(op_type, **extra):
    def f(x, name=None, **kwargs):
        attrs = dict(extra)
        for k in list(kwargs):
            if k in ("alpha", "beta", "threshold", "slope", "offset",
                     "approximate", "scale"):
                attrs[k] = kwargs[k]
        return _single(op_type, {"X": [x]}, attrs, dtype=x.dtype)

    f.__name__ = op_type
    return f


relu = _make_act("relu")
sigmoid = _make_act("sigmoid")
tanh = _make_act("tanh")
sqrt = _make_act("sqrt")
square = _make_act("square")
exp = _make_act("exp")
log = _make_act("log")
abs = _make_act("abs")
ceil = _make_act("ceil")
floor = _make_act("floor")
round = _make_act("round")
reciprocal = _make_act("reciprocal")
gelu = _make_act("gelu")
leaky_relu = _make_act("leaky_relu")
elu = _make_act("elu")
relu6 = _make_act("relu6")
softplus = _make_act("softplus")
softsign = _make_act("softsign")
swish = _make_act("swish")
hard_sigmoid = _make_act("hard_sigmoid")
hard_swish = _make_act("hard_swish")
logsigmoid = _make_act("logsigmoid")
erf = _make_act("erf")
sin = _make_act("sin")
cos = _make_act("cos")


def softmax(input, use_cudnn=False, name=None, axis=-1):
    return _single("softmax", {"X": [input]}, {"axis": axis},
                   dtype=input.dtype)


def log_softmax(input, axis=-1, name=None):
    return _single("log_softmax", {"X": [input]}, {"axis": axis},
                   dtype=input.dtype)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    return _single("matmul", {"X": [x], "Y": [y]},
                   {"transpose_X": transpose_x, "transpose_Y": transpose_y,
                    "alpha": float(alpha)}, dtype=x.dtype)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    return _single("mul", {"X": [x], "Y": [y]},
                   {"x_num_col_dims": x_num_col_dims,
                    "y_num_col_dims": y_num_col_dims}, dtype=x.dtype)


def _make_elementwise(op_type):
    def f(x, y, axis=-1, act=None, name=None):
        out = _single(op_type, {"X": [x], "Y": [y]}, {"axis": axis},
                      dtype=x.dtype)
        if act:
            out = _single(act, {"X": [out]}, {}, dtype=x.dtype)
        return out

    f.__name__ = op_type
    return f


elementwise_add = _make_elementwise("elementwise_add")
elementwise_sub = _make_elementwise("elementwise_sub")
elementwise_mul = _make_elementwise("elementwise_mul")
elementwise_div = _make_elementwise("elementwise_div")
elementwise_max = _make_elementwise("elementwise_max")
elementwise_min = _make_elementwise("elementwise_min")
elementwise_pow = _make_elementwise("elementwise_pow")
elementwise_mod = _make_elementwise("elementwise_mod")
elementwise_floordiv = _make_elementwise("elementwise_floordiv")


def maximum(x, y, name=None):
    return _single("maximum", {"X": [x], "Y": [y]}, {}, dtype=x.dtype)


def minimum(x, y, name=None):
    return _single("minimum", {"X": [x], "Y": [y]}, {}, dtype=x.dtype)


def logical_and(x, y, out=None, name=None):
    return _single("logical_and", {"X": [x], "Y": [y]}, {}, dtype="bool")


def logical_or(x, y, out=None, name=None):
    return _single("logical_or", {"X": [x], "Y": [y]}, {}, dtype="bool")


def logical_xor(x, y, out=None, name=None):
    return _single("logical_xor", {"X": [x], "Y": [y]}, {}, dtype="bool")


def logical_not(x, out=None, name=None):
    return _single("logical_not", {"X": [x]}, {}, dtype="bool")


def _make_reduce(op_type):
    def f(input, dim=None, keep_dim=False, name=None):
        if dim is None:
            attrs = {"reduce_all": True, "dim": [0], "keep_dim": keep_dim}
        else:
            attrs = {"dim": dim if isinstance(dim, (list, tuple)) else [dim],
                     "keep_dim": keep_dim, "reduce_all": False}
        return _single(op_type, {"X": [input]}, attrs, dtype=input.dtype)

    f.__name__ = op_type
    return f


reduce_sum = _make_reduce("reduce_sum")
reduce_mean = _make_reduce("reduce_mean")
reduce_max = _make_reduce("reduce_max")
reduce_min = _make_reduce("reduce_min")
reduce_prod = _make_reduce("reduce_prod")
reduce_all = _make_reduce("reduce_all")
reduce_any = _make_reduce("reduce_any")


def mean(x, name=None):
    return _single("mean", {"X": [x]}, {}, dtype=x.dtype)


def accuracy(input, label, k=1, correct=None, total=None):
    topk_out, topk_indices = topk(input, k=k)
    outs = apply_op("accuracy", "accuracy",
                    {"Out": [topk_out], "Indices": [topk_indices],
                     "Label": [label]}, {},
                    ["Accuracy", "Correct", "Total"], out_dtype="float32")
    return outs[0]


def topk(input, k=1, name=None):
    outs = apply_op("top_k", "top_k", {"X": [input]}, {"k": k},
                    ["Out", "Indices"], out_dtype=input.dtype)
    return outs[0], outs[1]


def one_hot(input, depth, allow_out_of_range=False):
    return _single("one_hot", {"X": [input]}, {"depth": depth},
                   dtype="float32")


def clip(x, min, max, name=None):
    return _single("clip", {"X": [x]}, {"min": float(min), "max": float(max)},
                   dtype=x.dtype)


def clip_by_norm(x, max_norm, name=None):
    return _single("clip_by_norm", {"X": [x]}, {"max_norm": float(max_norm)},
                   dtype=x.dtype)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    sq = _single("square", {"X": [x]}, {}, dtype=x.dtype)
    ssum = reduce_sum(sq, dim=axis, keep_dim=True)
    norm = _single("sqrt", {"X": [elementwise_add(
        ssum, fill_like_eps(ssum, epsilon))]}, {}, dtype=x.dtype)
    return elementwise_div(x, norm)


def fill_like_eps(ref, eps):
    from . import tensor as t

    return t.fill_constant(shape=[1], dtype=ref.dtype, value=eps)


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    ins = {"X": [label]}
    if prior_dist is not None:
        ins["PriorDist"] = [prior_dist]
    return _single("label_smooth", ins, {"epsilon": float(epsilon)},
                   dtype=dtype)


def pad(x, paddings, pad_value=0.0, name=None):
    return _single("pad", {"X": [x]},
                   {"paddings": list(paddings), "pad_value": pad_value},
                   dtype=x.dtype)


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    return _single("pad2d", {"X": [input]},
                   {"paddings": list(paddings), "mode": mode,
                    "pad_value": pad_value}, dtype=input.dtype)


def unsqueeze(input, axes, name=None):
    outs = apply_op("unsqueeze2", "unsqueeze2", {"X": [input]},
                    {"axes": list(axes)}, ["Out", "XShape"],
                    out_dtype=input.dtype)
    return outs[0]


def squeeze(input, axes, name=None):
    outs = apply_op("squeeze2", "squeeze2", {"X": [input]},
                    {"axes": list(axes)}, ["Out", "XShape"],
                    out_dtype=input.dtype)
    return outs[0]


def stack(x, axis=0, name=None):
    xs = x if isinstance(x, (list, tuple)) else [x]
    return apply_op("stack", "stack", {"X": list(xs)}, {"axis": axis},
                    ["Y"], out_dtype=xs[0].dtype)[0]


def unstack(x, axis=0, num=None):
    n = num or x.shape[axis]
    return apply_op("unstack", "unstack", {"X": [x]}, {"axis": axis},
                    {"Y": n}, out_dtype=x.dtype)


def expand(x, expand_times, name=None):
    return _single("expand", {"X": [x]}, {"expand_times": list(expand_times)},
                   dtype=x.dtype)


def expand_as(x, target_tensor, name=None):
    return _single("expand_as_v2", {"X": [x], "Y": [target_tensor]},
                   {"target_shape": list(target_tensor.shape)},
                   dtype=x.dtype)


def gather(input, index, overwrite=True):
    return _single("gather", {"X": [input], "Index": [index]}, {},
                   dtype=input.dtype)


def gather_nd(input, index, name=None):
    return _single("gather_nd", {"X": [input], "Index": [index]}, {},
                   dtype=input.dtype)


def scatter(input, index, updates, name=None, overwrite=True):
    return _single("scatter",
                   {"X": [input], "Ids": [index], "Updates": [updates]},
                   {"overwrite": overwrite}, dtype=input.dtype)


def slice(input, axes, starts, ends):
    return _single("slice", {"Input": [input]},
                   {"axes": list(axes), "starts": list(starts),
                    "ends": list(ends), "decrease_axis": []},
                   dtype=input.dtype)


def strided_slice(input, axes, starts, ends, strides):
    return _single("strided_slice", {"Input": [input]},
                   {"axes": list(axes), "starts": list(starts),
                    "ends": list(ends), "strides": list(strides)},
                   dtype=input.dtype)


def split(input, num_or_sections, dim=-1, name=None):
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "sections": [], "axis": dim}
    else:
        n = len(num_or_sections)
        attrs = {"num": 0, "sections": list(num_or_sections), "axis": dim}
    return apply_op("split", "split", {"X": [input]}, attrs, {"Out": n},
                    out_dtype=input.dtype)


def where(condition, x=None, y=None, name=None):
    return _single("where", {"Condition": [condition], "X": [x], "Y": [y]},
                   {}, dtype=x.dtype)


def cond_not_supported(*a, **k):
    raise NotImplementedError(
        "layers.cond: use lax.cond-backed control flow (planned)")


def flatten(x, axis=1, name=None):
    outs = apply_op("flatten2", "flatten2", {"X": [x]}, {"axis": axis},
                    ["Out", "XShape"], out_dtype=x.dtype)
    return outs[0]


# -- sequence ops (padded + Length mask; SURVEY.md §7 hard part (a)) -------

def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  length=None):
    if pool_type.upper() not in ("AVERAGE", "SUM", "SQRT", "LAST",
                                 "FIRST", "MAX"):
        # construction-time validation, matching the reference's InEnum
        # (sequence_pool_op.cc:69)
        raise ValueError("sequence_pool pool_type must be one of "
                         "average/sum/sqrt/last/first/max, got %r"
                         % (pool_type,))
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    outs = apply_op("sequence_pool", "sequence_pool", ins,
                    {"pooltype": pool_type.upper()}, ["Out", "MaxIndex"],
                    out_dtype=input.dtype)
    return outs[0]


def sequence_softmax(input, use_cudnn=False, name=None, length=None):
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    return _single("sequence_softmax", ins, {}, dtype=input.dtype)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    return _single("sequence_mask", {"X": [x]},
                   {"maxlen": maxlen or -1, "out_dtype": dtype}, dtype=dtype)


def sequence_expand(x, y, ref_level=-1, name=None):
    return _single("sequence_expand", {"X": [x], "Y": [y]},
                   {"ref_level": ref_level}, dtype=x.dtype)


def sequence_reshape(input, new_dim):
    return _single("sequence_reshape", {"X": [input]}, {"new_dim": new_dim},
                   dtype=input.dtype)


def sequence_reverse(x, name=None, length=None):
    ins = {"X": [x]}
    if length is not None:
        ins["Length"] = [length]
    return apply_op("sequence_reverse", "sequence_reverse", ins, {}, ["Y"],
                    out_dtype=x.dtype)[0]


def image_resize(input, out_shape=None, scale=None, resample="NEAREST",
                 name=None):
    if out_shape is None:
        h, w = input.shape[2] * scale, input.shape[3] * scale
    else:
        h, w = out_shape
    return _single("interp_nearest", {"X": [input]},
                   {"out_h": int(h), "out_w": int(w)}, dtype=input.dtype)


resize_nearest = image_resize


def scaled_dot_product_attention(q, k, v, key_bias=None, causal=False,
                                 sm_scale=None, attn_dropout_prob=0.0,
                                 is_test=False, name=None):
    """Fused attention over [B, H, S, D] q/k/v; optional [B, Sk] additive
    key bias. Lowers to the Pallas flash-attention kernel on TPU
    (paddle_tpu/ops/pallas/); reference fuses only inference attention
    (`operators/fused/multihead_matmul_op.cu`)."""
    ins = {"Q": [q], "K": [k], "V": [v]}
    if key_bias is not None:
        ins["KeyBias"] = [key_bias]
    return _single("scaled_dot_product_attention", ins,
                   {"causal": causal,
                    "sm_scale": -1.0 if sm_scale is None else float(sm_scale),
                    "attn_dropout_prob": float(attn_dropout_prob),
                    "is_test": is_test}, dtype=q.dtype)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None):
    """Reference: layers/nn.py uniform_random -> uniform_random op."""
    return _single("uniform_random", {},
                   {"shape": list(shape), "min": float(min),
                    "max": float(max), "seed": seed, "dtype": dtype},
                   dtype=dtype)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    """Reference: layers/nn.py gaussian_random -> gaussian_random op."""
    return _single("gaussian_random", {},
                   {"shape": list(shape), "mean": float(mean),
                    "std": float(std), "seed": seed, "dtype": dtype},
                   dtype=dtype)
