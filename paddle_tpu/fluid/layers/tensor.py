"""Tensor creation/manipulation layers (reference:
`python/paddle/fluid/layers/tensor.py`)."""
from __future__ import annotations

import numpy as np

from .. import framework
from ..framework import Variable, in_dygraph_mode
from ..layer_helper import LayerHelper, apply_op
from ...core.types import normalize_dtype

__all__ = [
    "data", "fill_constant", "fill_constant_batch_size_like", "cast",
    "concat", "assign", "create_tensor", "create_parameter",
    "create_global_var", "argmax",
    "argmin", "argsort", "zeros", "ones", "zeros_like", "ones_like",
    "reverse", "range", "linspace", "reshape", "transpose", "scale",
    "shape", "cumsum", "increment", "eye", "diag", "tril", "triu",
    "take_along_axis", "tensor_array_to_tensor",
]


def _single(op_type, inputs, attrs, dtype=None):
    return apply_op(op_type, op_type, inputs, attrs, ["Out"],
                    out_dtype=dtype)[0]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True, type=None):
    """Declare an input variable (reference: layers/io.py data /
    fluid.data). With append_batch_size, -1 is prepended."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = framework.default_main_program().current_block()
    return block.create_var(
        name=name, shape=shape, dtype=dtype, is_data=True,
        stop_gradient=stop_gradient, persistable=False,
        lod_level=lod_level)


def take_along_axis(input, index, axis, name=None):
    """Batched gather: out[..., i, ...] = input[..., index[..., i, ...], ...]
    along `axis`, numpy take_along_axis semantics (index broadcasts against
    input on the non-axis dims)."""
    return apply_op("take_along_axis", "take_along_axis",
                    {"Input": [input], "Index": [index]},
                    {"Axis": int(axis)}, ["Result"],
                    out_dtype=input.dtype)[0]


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    dtype = normalize_dtype(dtype)
    if in_dygraph_mode():
        from ..dygraph import base as dy_base

        return dy_base.trace_op(
            "fill_constant", {}, {"shape": list(shape), "dtype": dtype,
                                  "value": float(value)}, ["Out"])[0]
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="fill_constant", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    return _single("fill_constant_batch_size_like", {"Input": [input]},
                   {"shape": list(shape), "dtype": normalize_dtype(dtype),
                    "value": float(value), "input_dim_idx": input_dim_idx,
                    "output_dim_idx": output_dim_idx}, dtype=dtype)


def cast(x, dtype):
    dtype = normalize_dtype(dtype)
    return _single("cast", {"X": [x]}, {"out_dtype": dtype}, dtype=dtype)


def concat(input, axis=0, name=None):
    return _single("concat", {"X": list(input)}, {"axis": axis},
                   dtype=input[0].dtype)


def assign(input, output=None):
    if isinstance(input, np.ndarray):
        attrs = {"shape": list(input.shape),
                 "dtype": normalize_dtype(input.dtype)}
        key = ("fp32_values" if input.dtype in (np.float32, np.float64)
               else "int32_values" if input.dtype == np.int32
               else "int64_values")
        attrs[key] = input.astype(
            "float64" if "fp" in key else input.dtype).flatten().tolist()
        if in_dygraph_mode():
            from ..dygraph import base as dy_base

            return dy_base.trace_op("assign_value", {}, attrs, ["Out"])[0]
        helper = LayerHelper("assign_value")
        out = output or helper.create_variable_for_type_inference(
            normalize_dtype(input.dtype))
        helper.append_op(type="assign_value", outputs={"Out": [out]},
                         attrs=attrs)
        return out
    if in_dygraph_mode():
        from ..dygraph import base as dy_base

        return dy_base.trace_op("assign", {"X": [input]}, {}, ["Out"])[0]
    helper = LayerHelper("assign")
    out = output or helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="assign", inputs={"X": [input]},
                     outputs={"Out": [out]})
    return out


def create_tensor(dtype, name=None, persistable=False):
    block = framework.default_main_program().current_block()
    return block.create_var(name=name, dtype=dtype, persistable=persistable,
                            shape=())


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Reference: layers/tensor.py create_parameter."""
    from ..param_attr import ParamAttr

    helper = LayerHelper("create_parameter")
    if attr is None:
        attr = ParamAttr(name=name)
    elif name is not None and attr.name is None:
        attr.name = name
    return helper.create_parameter(attr, list(shape), dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        name=name or framework.unique_name("global_var"),
        shape=list(shape), dtype=dtype, persistable=persistable)
    from ..initializer import ConstantInitializer

    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def argmax(x, axis=0, name=None):
    return _single("arg_max", {"X": [x]}, {"axis": axis}, dtype="int64")


def argmin(x, axis=0, name=None):
    return _single("arg_min", {"X": [x]}, {"axis": axis}, dtype="int64")


def argsort(input, axis=-1, descending=False, name=None):
    outs = apply_op("argsort", "argsort", {"X": [input]},
                    {"axis": axis, "descending": descending},
                    ["Out", "Indices"], out_dtype=input.dtype)
    return outs[0], outs[1]


def zeros(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    return _single("fill_any_like", {"X": [x]}, {"value": 0.0, "dtype": -1},
                   dtype=x.dtype)


def ones_like(x, out=None):
    return _single("fill_any_like", {"X": [x]}, {"value": 1.0, "dtype": -1},
                   dtype=x.dtype)


def reverse(x, axis):
    return _single("flip", {"X": [x]},
                   {"axis": axis if isinstance(axis, (list, tuple))
                    else [axis]}, dtype=x.dtype)


def range(start, end, step, dtype="float32"):
    s = fill_constant([1], dtype, start) if not isinstance(
        start, Variable) else start
    e = fill_constant([1], dtype, end) if not isinstance(
        end, Variable) else end
    st = fill_constant([1], dtype, step) if not isinstance(
        step, Variable) else step
    return _single("range", {"Start": [s], "End": [e], "Step": [st]}, {},
                   dtype=dtype)


def linspace(start, stop, num, dtype="float32"):
    s = fill_constant([1], dtype, start)
    e = fill_constant([1], dtype, stop)
    n = fill_constant([1], "int32", num)
    return _single("linspace", {"Start": [s], "Stop": [e], "Num": [n]},
                   {"dtype": dtype}, dtype=dtype)


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    outs = apply_op("reshape2", "reshape2", {"X": [x]},
                    {"shape": [int(s) for s in shape]}, ["Out", "XShape"],
                    out_dtype=x.dtype)
    return outs[0]


def transpose(x, perm, name=None):
    outs = apply_op("transpose2", "transpose2", {"X": [x]},
                    {"axis": list(perm)}, ["Out", "XShape"],
                    out_dtype=x.dtype)
    return outs[0]


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    out = _single("scale", {"X": [x]},
                  {"scale": float(scale), "bias": float(bias),
                   "bias_after_scale": bias_after_scale}, dtype=x.dtype)
    if act:
        out = _single(act, {"X": [out]}, {}, dtype=x.dtype)
    return out


def shape(input):
    return _single("shape", {"Input": [input]}, {}, dtype="int32")


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    return _single("cumsum", {"X": [x]},
                   {"axis": axis, "exclusive": exclusive, "reverse": reverse},
                   dtype=x.dtype)


def increment(x, value=1.0, in_place=True):
    if in_dygraph_mode():
        from ..dygraph import base as dy_base

        return dy_base.trace_op("increment", {"X": [x]}, {"step": value},
                                ["Out"])[0]
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(
        x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32"):
    return _single("eye", {}, {"num_rows": num_rows,
                               "num_columns": num_columns or -1,
                               "dtype": normalize_dtype(dtype)}, dtype=dtype)


def diag(diagonal):
    return _single("diag_v2", {"X": [diagonal]}, {"offset": 0},
                   dtype=diagonal.dtype)


def tril(x, diagonal=0, name=None):
    return _single("tril_triu", {"X": [x]},
                   {"diagonal": diagonal, "lower": True}, dtype=x.dtype)


def triu(x, diagonal=0, name=None):
    return _single("tril_triu", {"X": [x]},
                   {"diagonal": diagonal, "lower": False}, dtype=x.dtype)


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    """Concat (or stack) every entry of a TensorArray along `axis`
    (reference: tensor.py:362 / tensor_array_to_tensor_op.cc); also
    returns the per-entry extents along that axis."""
    outs = apply_op("tensor_array_to_tensor", "tensor_array_to_tensor",
                    {"X": [input]},
                    {"axis": axis, "use_stack": use_stack},
                    ["Out", "OutIndex"])
    return outs[0], outs[1]
