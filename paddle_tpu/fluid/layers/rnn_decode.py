"""Decoding API: RNNCell / BeamSearchDecoder / dynamic_decode.

Reference parity: `python/paddle/fluid/layers/rnn.py` (3254 LoC) —
`dynamic_decode` drives a Decoder's step function inside a While loop;
`BeamSearchDecoder` expands beams with the beam_search op and finalizes
with gather_tree. TPU-native: the step loop unrolls to `max_step_num`
(static shapes; XLA folds the per-step computations), the per-step beam
expansion is the jit-able `beam_search` op (ops/beam_search_ops.py) and
finalization backtracks with `gather_tree`.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .. import framework
from ..layer_helper import LayerHelper, apply_op
from . import nn as nn_layers
from . import tensor as tensor_layers

__all__ = ["RNNCell", "GRUCell", "BeamSearchDecoder", "dynamic_decode"]


class RNNCell:
    """Reference: layers/rnn.py RNNCell — call(inputs, states) ->
    (outputs, new_states)."""

    def call(self, inputs, states):
        raise NotImplementedError

    def __call__(self, inputs, states):
        return self.call(inputs, states)


class GRUCell(RNNCell):
    def __init__(self, hidden_size, param_attr=None, name="gru_cell"):
        self.hidden_size = hidden_size
        self._name = name
        self._param_attr = param_attr
        self._w_ih = None
        self._w_hh = None

    def call(self, inputs, states):
        h = states
        if self._w_ih is None:
            # create ONCE and share across decode steps (a fresh
            # create_parameter per call would mint new unique-named,
            # newly-initialized weights every timestep)
            helper = LayerHelper(self._name,
                                 param_attr=self._param_attr)
            in_dim = int(inputs.shape[-1])
            self._w_ih = helper.create_parameter(
                helper.param_attr,
                shape=[in_dim, 3 * self.hidden_size],
                dtype=inputs.dtype)
            self._w_hh = helper.create_parameter(
                helper.param_attr,
                shape=[self.hidden_size, 3 * self.hidden_size],
                dtype=inputs.dtype)
        w_ih, w_hh = self._w_ih, self._w_hh
        gi = nn_layers.matmul(inputs, w_ih)
        gh = nn_layers.matmul(h, w_hh)
        gi_r, gi_z, gi_n = nn_layers.split(gi, 3, dim=-1)
        gh_r, gh_z, gh_n = nn_layers.split(gh, 3, dim=-1)
        r = nn_layers.sigmoid(gi_r + gh_r)
        z = nn_layers.sigmoid(gi_z + gh_z)
        n = nn_layers.tanh(gi_n + r * gh_n)
        new_h = (1.0 - z) * n + z * h
        return new_h, new_h


class BeamSearchDecoder:
    """Reference: layers/rnn.py BeamSearchDecoder. cell outputs logits
    via output_fn; ids feed back through embedding_fn."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        """initial_cell_states: [batch, ...] -> tiled to beams."""
        state = initial_cell_states
        batch = state.shape[0]
        # tile to [batch*beam, ...]
        state_t = nn_layers.expand(
            nn_layers.unsqueeze(state, axes=[1]),
            expand_times=[1, self.beam_size] + [1] * (len(state.shape)
                                                      - 1))
        state_t = tensor_layers.reshape(
            state_t, [batch * self.beam_size] + list(state.shape[1:]))
        ids = tensor_layers.fill_constant(
            [batch, self.beam_size], "int64", self.start_token)
        scores = tensor_layers.assign(
            np.tile(np.array([[0.0] + [-1e9] * (self.beam_size - 1)],
                             "float32"), (batch, 1)))
        return ids, scores, state_t

    def step(self, ids, scores, cell_states):
        batch, beam = ids.shape[0], self.beam_size
        inp = self.embedding_fn(tensor_layers.reshape(ids, [batch * beam])) \
            if self.embedding_fn else nn_layers.one_hot(
                tensor_layers.reshape(ids, [batch * beam, 1]), depth=64)
        cell_out, next_states = self.cell(inp, cell_states)
        logits = self.output_fn(cell_out) if self.output_fn else cell_out
        logp = nn_layers.log_softmax(logits)
        vocab = int(logp.shape[-1])
        logp3 = tensor_layers.reshape(logp, [batch, beam, vocab])
        outs = apply_op(
            "beam_search", "beam_search",
            {"pre_ids": [ids], "pre_scores": [scores],
             "scores": [logp3]},
            {"beam_size": beam, "end_id": self.end_token},
            ["selected_ids", "selected_scores", "parent_idx"])
        sel_ids, sel_scores, parents = outs
        # reorder cell states by parent beam
        flat_parent = parents + tensor_layers.assign(
            (np.arange(batch) * beam).reshape(batch, 1).astype("int64"))
        flat_parent = tensor_layers.reshape(flat_parent, [batch * beam])
        next_states = nn_layers.gather(next_states, flat_parent)
        return sel_ids, sel_scores, parents, next_states


def dynamic_decode(decoder, inits=None, max_step_num=20, output_time_major
                   =False, return_length=False, **kwargs):
    """Unrolled decode loop (reference: layers/rnn.py dynamic_decode).
    Returns (ids [batch, T, beam], scores [batch, beam]) after
    gather_tree backtracking."""
    ids, scores, states = decoder.initialize(inits)
    step_ids, step_parents = [], []
    for _ in range(max_step_num):
        ids, scores, parents, states = decoder.step(ids, scores, states)
        step_ids.append(ids)
        step_parents.append(parents)
    ids_stack = nn_layers.stack(step_ids, axis=0)      # [T, batch, beam]
    par_stack = nn_layers.stack(step_parents, axis=0)
    outs = apply_op("gather_tree", "gather_tree",
                    {"Ids": [ids_stack], "Parents": [par_stack]},
                    {}, ["Out"])[0]
    if not output_time_major:
        outs = tensor_layers.transpose(outs, [1, 0, 2])
    if return_length:
        # per-beam valid length: tokens before/at the first end token
        # (reference dynamic_decode returns sequence_lengths)
        end_id = getattr(decoder, "end_token", 1)
        time_axis = 0 if output_time_major else 1
        from .control_flow import equal, greater_than

        # reference dynamic_decode counts the step emitting the end
        # token: length = index of the first end token + 1 (whole T when
        # no end token appears). cumsum of is-end along time marks
        # positions strictly after the first end.
        is_end = tensor_layers.cast(
            equal(outs,
                  tensor_layers.fill_constant([1], outs.dtype, end_id)),
            "int64")
        after_first_end = tensor_layers.cast(
            greater_than(
                tensor_layers.cumsum(is_end, axis=time_axis),
                tensor_layers.fill_constant([1], "int64", 1)),
            "int64")
        t_extent = outs.shape[time_axis]
        lengths = nn_layers.elementwise_sub(
            tensor_layers.fill_constant([1], "int64", t_extent),
            nn_layers.reduce_sum(after_first_end, dim=time_axis))
        return outs, scores, lengths
    return outs, scores
