"""fluid.nets — composite network builders.

Reference parity: `python/paddle/fluid/nets.py` — simple_img_conv_pool,
img_conv_group, sequence_conv_pool, glu, scaled_dot_product_attention.
Pure compositions of layers builders; XLA fuses the pieces.
"""
from __future__ import annotations

from . import layers


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act)
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    tmp = input
    if isinstance(conv_num_filter, int):
        conv_num_filter = [conv_num_filter]

    def _expand(v):
        return v if isinstance(v, (list, tuple)) else \
            [v] * len(conv_num_filter)

    paddings = _expand(conv_padding)
    fsizes = _expand(conv_filter_size)
    with_bn = _expand(conv_with_batchnorm)
    drop_rates = _expand(conv_batchnorm_drop_rate)
    pattrs = param_attr if isinstance(param_attr, (list, tuple)) else \
        [param_attr] * len(conv_num_filter)

    for i, nf in enumerate(conv_num_filter):
        act = conv_act if not with_bn[i] else None
        tmp = layers.conv2d(input=tmp, num_filters=nf,
                            filter_size=fsizes[i], padding=paddings[i],
                            param_attr=pattrs[i], act=act)
        if with_bn[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            if drop_rates[i]:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rates[i])
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None):
    from .layer_helper import LayerHelper, apply_op

    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act)
    d = int(input.shape[-1])
    filt = helper.create_parameter(
        helper.param_attr, shape=[filter_size * d, num_filters],
        dtype=input.dtype)
    conv = apply_op(helper, "sequence_conv",
                    {"X": [input], "Filter": [filt]},
                    {"contextLength": filter_size,
                     "contextStart": -(filter_size // 2)},
                    ["Out"], out_dtype=input.dtype)[0]
    conv = helper.append_activation(conv)
    return layers.sequence_pool(input=conv, pool_type=pool_type)


def glu(input, dim=-1):
    """Gated linear unit (reference: nets.py glu): split + sigmoid gate."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Reference: nets.py scaled_dot_product_attention over [B, S, D]."""
    from .layer_helper import apply_op

    b = queries.shape[0]
    sq = queries.shape[1]
    d = int(queries.shape[-1])
    dh = d // num_heads

    def to_heads(x):
        s = x.shape[1]
        x = layers.reshape(x, [b if b > 0 else -1, s, num_heads, dh])
        return layers.transpose(x, [0, 2, 1, 3])

    q, k, v = to_heads(queries), to_heads(keys), to_heads(values)
    ctx = apply_op("scaled_dot_product_attention",
                   "scaled_dot_product_attention",
                   {"Q": [q], "K": [k], "V": [v]},
                   {"attn_dropout_prob": dropout_rate}, ["Out"],
                   out_dtype=queries.dtype)[0]
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    return layers.reshape(ctx, [b if b > 0 else -1, sq, d])
