"""Block lowering: a Program block -> ONE jitted XLA computation.

This replaces the reference's entire execution stack — the op-by-op C++
Executor loop (`framework/executor.cc:471`), kernel dispatch
(`operator.cc:908-1030`), data transforms, memory-reuse passes
(`ir/memory_optimize_pass/`), fusion passes (`ir/*fuse*`), and the SSA
multi-device executors (`details/fast_threaded_ssa_graph_executor.cc`).
TPU-first: trace the op list once into a single jax function, let XLA fuse
and schedule it, cache the compiled executable keyed by
(program version, feed shapes); data-parallel programs wrap the same
function in `jax.shard_map` over a Mesh so collective ops emit ICI
collectives (SURVEY.md §3B "the whole SSA machinery collapses into XLA SPMD
partitioning").

Autodiff: `append_backward` plants a single `backward` pseudo-op; lowering
runs the forward segment under `jax.vjp` and binds each requested `X@GRAD`
(replacing per-op GradOpMakers, `grad_op_desc_maker.h`).

Mutable Scope semantics vs XLA purity (SURVEY.md §7 hard part (c)): the
lowered function is pure — scope-resident state (params, optimizer moments,
BN running stats) enters as inputs and leaves as outputs; variable rebinding
inside the block is SSA-ified by the name->value environment.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List

import numpy as np

from . import framework
from .. import ops as ops_lib
from ..core.rng import make_key
from ..core.types import to_numpy_dtype

# Ops that exist only for runtime bookkeeping in the reference; under XLA
# they are no-ops (stream sync is dataflow; comm init is mesh construction).
_SKIP_OPS = frozenset({
    "feed", "fetch", "c_gen_nccl_id", "gen_nccl_id", "c_comm_init",
    "c_comm_init_all", "c_wait_compute", "c_wait_comm", "barrier",
    "nop",
    # PS-mode markers: the host-side PSCommunicator performs the actual
    # RPC around each jitted step (distributed/ps.py)
    "send", "recv", "send_barrier", "fetch_barrier", "checkpoint_notify",
})


class LoweredFunction:
    """A compiled block: callable (feeds, states_mut, states_ro, seed) ->
    (fetches, states'). states_mut (rebound by the block: params, moments,
    running stats) are donated so XLA updates them in place on HBM;
    feed_donate records whether the feed argument is donated too
    (FLAGS_tpu_donate_feed_buffers) — the executor then guards
    caller-owned device arrays before the call."""

    __slots__ = ("jitted", "state_in_names", "state_out_names",
                 "state_mut_names", "state_ro_names",
                 "fetch_names", "feed_names", "mesh", "dp_axis",
                 "auto_plan", "feed_donate", "sharded_state",
                 "sparse_tables", "aot_compiled", "cc_fingerprint",
                 "cc_prev")

    def __init__(self, jitted, feed_names, state_in_names, state_out_names,
                 state_mut_names, state_ro_names, fetch_names, mesh=None,
                 dp_axis=None, auto_plan=None, feed_donate=False,
                 sharded_state=None, sparse_tables=None):
        self.jitted = jitted
        self.feed_names = feed_names
        self.state_in_names = state_in_names
        self.state_out_names = state_out_names
        self.state_mut_names = state_mut_names
        self.state_ro_names = state_ro_names
        self.fetch_names = fetch_names
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.auto_plan = auto_plan
        self.feed_donate = feed_donate
        # {name: parallel.sharded_update.ShardInfo} when the compiled
        # step keeps optimizer state sharded over the dp axis (ZeRO-1);
        # the executor lays those scope arrays out as flat 1/N buffers
        self.sharded_state = sharded_state
        # {name: embedding.RowShardInfo} when the step keeps embedding
        # tables (+ per-row moments) vocab-sharded over the dp axis;
        # the executor lays those scope arrays out as row-sharded
        # (padded_rows, dim) buffers (paddle_tpu/embedding)
        self.sparse_tables = sparse_tables
        # memoized AOT-compiled artifact for the report surfaces
        # (donation_report / overlap_report) — one XLA compile serves
        # every audit of this executable instead of one per call
        self.aot_compiled = None
        # persistent compile-cache classification (fluid/compile_cache,
        # FLAGS_tpu_compile_cache_dir): the program fingerprint and the
        # prior compile's index sentinel (None = first-ever compile)
        self.cc_fingerprint = None
        self.cc_prev = None


def _sub_block_idxs(op):
    idxs = []
    for a in ("sub_block", "sub_block_t", "sub_block_f"):
        if a in op.attrs:
            idxs.append(op.attrs[a])
    idxs.extend(op.attrs.get("sub_blocks", []))
    return idxs


def _op_reads_writes(op):
    """(reads, writes) of an op, looking through control-flow sub-blocks
    (a var read only inside a while body is still block-level state).
    Sub-block writes to persistable vars also count as reads: the carry
    needs their incoming value so the functional loop can thread them."""
    reads = list(op.input_arg_names)
    writes = list(op.output_arg_names)
    prog = op.block.program
    for bi in _sub_block_idxs(op):
        blk = prog.block(bi)
        # scan xs slices (and the iteration-index var) are produced by
        # the loop machinery itself, not by any sub-block op — they are
        # never external reads
        produced_local = set(op.attrs.get("xs_slice", []))
        if op.attrs.get("iter_var"):
            produced_local.add(op.attrs["iter_var"])
        for sop in blk.ops:
            sr, sw = _op_reads_writes(sop)
            for n in sr:
                if n not in produced_local:
                    reads.append(n)
            for n in sw:
                v = blk._find_var_recursive(n)
                if v is not None and v.persistable \
                        and n not in produced_local:
                    reads.append(n)
                produced_local.add(n)
                writes.append(n)
    return reads, writes


def analyze_block(block, feed_names, fetch_names):
    """Dataflow analysis: which names are scope state in/out."""
    produced = set(feed_names)
    state_in: List[str] = []
    state_in_set = set()
    for op in block.ops:
        op_reads, op_writes = _op_reads_writes(op)
        for name in op_reads:
            if name not in produced and name not in state_in_set:
                state_in.append(name)
                state_in_set.add(name)
        for name in op_writes:
            produced.add(name)
    for name in fetch_names:
        if name not in produced and name not in state_in_set:
            state_in.append(name)
            state_in_set.add(name)

    # state outputs: names written by ops that are persistable vars or
    # rebind scope-resident inputs (param updates, running stats, ...)
    state_out: List[str] = []
    seen = set()
    for op in block.ops:
        for name in _op_reads_writes(op)[1]:
            if name in seen:
                continue
            persistable = False
            v = block._find_var_recursive(name)
            if v is not None and v.persistable:
                persistable = True
            if persistable or name in state_in_set:
                seen.add(name)
                state_out.append(name)
    return state_in, state_out


def _prov_scope(op, op_idx):
    """Provenance stamp of one traced op (FLAGS_tpu_op_provenance; see
    observability/attribution.py): a jax.named_scope whose marker rides
    the name stack into the StableHLO debug locations AND the optimized
    HLO's op_name metadata — zero runtime cost, one context manager per
    op at trace time. Control-flow sub-block ops nest inside their
    parent op's scope; the innermost marker is the true source."""
    from ..observability import attribution as _attr

    return _attr.op_scope(op, op_idx)


def _exec_op(op, env, key0, op_idx, amp_lists=None):
    t = op.type
    if t in _SKIP_OPS:
        return
    with _prov_scope(op, op_idx):
        return _exec_op_stamped(op, env, key0, op_idx,
                                amp_lists=amp_lists)


def _exec_op_stamped(op, env, key0, op_idx, amp_lists=None):
    import jax
    import jax.numpy as jnp

    t = op.type
    if t == "while":
        return _exec_while(op, env, key0, op_idx, amp_lists)
    if t == "scan":
        return _exec_scan(op, env, key0, op_idx, amp_lists)
    if t == "cond":
        return _exec_cond(op, env, key0, op_idx, amp_lists)
    if t == "switch_case":
        return _exec_switch_case(op, env, key0, op_idx, amp_lists)
    if t == "conditional_block":
        return _exec_conditional_block(op, env, key0, op_idx, amp_lists)
    # vocab-sharded embedding engine (paddle_tpu/embedding): under an
    # active sparse plan, lookup ops over TableShards and the sparse
    # optimizer ops route to the engine's trace rules; any OTHER op
    # touching an engine value fails loudly (no-op when no plan is
    # active — a single contextvar read)
    from ..embedding import engine as _emb_engine

    if _emb_engine.maybe_exec(op, env):
        return
    opdef = ops_lib.get_op(t)
    ins = {}
    for slot, names in op.input_names.items():
        if not names:
            continue
        try:
            ins[slot] = [env[n] for n in names]
        except KeyError as e:
            from ..core.errors import NotFoundError, attach_op_callstack

            attach_op_callstack(NotFoundError(
                "op %s: input var %s not materialized (feed it or run "
                "the startup program)" % (t, e)), op)
    # AMP policy (reference: fp16_utils.py cast insertion; here the
    # casts are applied at trace time and fused by XLA)
    if amp_lists is not None:
        ins = _apply_amp_casts(t, op, ins, amp_lists)
    # fp8 tier: inputs of fp8-white-list ops additionally qdq through
    # e4m3 at their per-tensor delayed scale (active only inside the
    # build_block_fn vjp region — the contextvar is unset elsewhere)
    fp8 = _FP8_TRACE.get()
    if fp8 is not None and t in fp8.ops:
        ins = fp8.quantize_inputs(op, ins, env)
    else:
        fp8 = None
    attrs = dict(op.attrs)
    if opdef.needs_rng:
        attrs["_rng_key"] = jax.random.fold_in(key0, op_idx)
    try:
        # tensor parallelism (parallel/tensor_parallel.py): under an
        # active TP plan, an op consuming a model-sharded weight lowers
        # to the local partial compute + its model-axis collective —
        # same contextvar routing as the sparse engine above
        from ..parallel import tensor_parallel as _tp_engine

        tp_outs = _tp_engine.maybe_compute(op, ins, attrs)
        if tp_outs is not None:
            outs = ops_lib.normalize_outs(tp_outs)
        elif opdef.no_jit and any(
                isinstance(v, jax.core.Tracer)
                for vs in ins.values() for v in vs):
            outs = _host_callback_op(opdef, op, ins, attrs)
        else:
            outs = ops_lib.normalize_outs(opdef.compute(ins, attrs))
    except Exception as e:  # attach the op's python creation site
        from ..core.errors import attach_op_callstack

        attach_op_callstack(e, op)
    for slot, names in op.output_names.items():
        vals = outs.get(slot, [])
        for n, v in zip(names, vals):
            env[n] = v
    if fp8 is not None:
        # fp8 tier: the op's outputs carry the e5m2 gradient site (the
        # cotangent flowing back INTO this op quantizes through e5m2)
        fp8.wrap_outputs(op, env)


class _AmpTracePolicy:
    """The AMP lowering 'pass', trace-time form: per-op white/black-list
    casts at list boundaries (white-list matmul/conv inputs drop to the
    16-bit compute dtype for the MXU; black-list softmax/norm/reduce
    inputs lift back to fp32), applied as the block traces so XLA fuses
    every inserted convert. Parameterized by `program._amp_dtype`
    (bf16 default, fp16 with loss scaling) and honoring the lists'
    `black_varnames` (vars pinned to fp32 by name). Gray-list ops
    follow their inputs — no casts — exactly the reference policy."""

    __slots__ = ("lists", "low")

    def __init__(self, lists, dtype_name):
        import jax.numpy as jnp

        self.lists = lists
        self.low = jnp.float16 if str(dtype_name) == "float16" \
            else jnp.bfloat16

    # duck-type the raw AutoMixedPrecisionLists surface for callers
    # that inspect the policy (analysis/contracts.py, tests)
    @property
    def white_list(self):
        return self.lists.white_list

    @property
    def black_list(self):
        return self.lists.black_list


def _amp_trace_policy(program):
    """program -> _AmpTracePolicy (or None when AMP is off)."""
    if not getattr(program, "_amp", False):
        return None
    lists = getattr(program, "_amp_lists", None)
    if lists is None:
        return None
    return _AmpTracePolicy(lists,
                           getattr(program, "_amp_dtype", "bfloat16"))


def _apply_amp_casts(t, op, ins, amp):
    """Insert the list-boundary casts for one op's inputs (see
    _AmpTracePolicy). `amp` may be an _AmpTracePolicy or a raw
    AutoMixedPrecisionLists (legacy callers: bf16, no black vars)."""
    import jax.numpy as jnp

    lists = amp.lists if isinstance(amp, _AmpTracePolicy) else amp
    low = amp.low if isinstance(amp, _AmpTracePolicy) else jnp.bfloat16
    black_vars = getattr(lists, "black_varnames", None) or ()

    def cast_ins(src, dst):
        out = {}
        for s, vs in ins.items():
            names = op.input_names.get(s, [])
            out[s] = [
                v.astype(dst)
                if hasattr(v, "dtype") and v.dtype == src
                and (i >= len(names) or names[i] not in black_vars)
                else v
                for i, v in enumerate(vs)]
        return out

    if t in lists.white_list:
        return cast_ins(jnp.float32, low)
    if t in lists.black_list:
        return cast_ins(low, jnp.float32)
    return ins


# ---------------------------------------------------------------------------
# fp8 training tier (amp_dtype="float8_e4m3"): trace-time e4m3/e5m2
# quantize-dequantize sites with per-tensor delayed scaling
# ---------------------------------------------------------------------------

import contextvars as _contextvars

#: the active _Fp8Trace for the CURRENT forward/backward trace (set by
#: build_block_fn around the jax.vjp region only — post-backward ops
#: never quantize). contextvar: safe under concurrent warmup traces.
_FP8_TRACE = _contextvars.ContextVar("fp8_trace", default=None)

_FP8_OBS_SUFFIX = "@FP8_AMAX_OBS"
_FP8_GTAP_SUFFIX = "@FP8_GTAP"


@contextlib.contextmanager
def _fp8_trace_scope(trace):
    tok = _FP8_TRACE.set(trace)
    try:
        yield
    finally:
        _FP8_TRACE.reset(tok)


def _fp8_qdq(x, scale, fp8_dtype, fmax):
    """Straight-through e4m3 quantize-dequantize at the delayed scale:
    forward value is round-trip through fp8 (saturated at the format
    max, exactly what XLA pattern-matches into a native fp8 matmul
    operand on TPU), backward cotangent passes through UNCHANGED (the
    reference quant_ops' stop_gradient STE — without it, JAX's
    convert transpose would quantize the cotangent to e4m3 too)."""
    import jax
    import jax.numpy as jnp

    xf = x.astype(jnp.float32) * scale
    q = (jnp.clip(xf, -fmax, fmax).astype(fp8_dtype)
         .astype(jnp.float32) / scale).astype(x.dtype)
    return x + jax.lax.stop_gradient(q - x)


def _fp8_grad_qdq_site_make():
    """The e5m2 gradient site, built lazily (module import must not
    require jax). Identity forward on the fp8 op's OUTPUT; the bwd rule
    (i) quantize-dequantizes the incoming cotangent dY through e5m2 at
    the delayed grad scale — so BOTH backward matmuls (dX and dW)
    consume the fp8 gradient, the Transformer-Engine recipe — and
    (ii) emits amax(|dY|) as the cotangent of the synthetic `gtap`
    input, the vocab-sharded-embedding tap idiom carrying the
    observation legally out of jax.vjp."""
    import jax
    import jax.numpy as jnp

    @jax.custom_vjp
    def site(y, gtap, gscale, fmax):
        return y

    def fwd(y, gtap, gscale, fmax):
        return y, (gscale, fmax)

    def bwd(res, ct):
        gscale, fmax = res
        ctf = ct.astype(jnp.float32)
        amax = jnp.max(jnp.abs(ctf))
        q = (jnp.clip(ctf * gscale, -fmax, fmax)
             .astype(jnp.float8_e5m2).astype(jnp.float32)
             / gscale).astype(ct.dtype)
        return (q, amax.astype(jnp.float32),
                jnp.zeros_like(gscale), jnp.zeros_like(fmax))

    site.defvjp(fwd, bwd)
    return site


_fp8_grad_qdq_site = None


class _Fp8Trace:
    """Per-trace fp8 site router (one per build_block_fn vjp region),
    driven by the backward op's ``fp8_delayed_scaling`` attr. Inputs of
    fp8-white-list ops qdq through e4m3 at their delayed scale (amax
    observed into ``<var>@FP8_AMAX_OBS`` env entries, which ride the
    vjp aux env out); outputs get the e5m2 grad site fed by the
    ``<var>@FP8_GTAP`` synthetic diff vars."""

    __slots__ = ("cfg", "ops")

    def __init__(self, cfg):
        self.cfg = cfg
        self.ops = frozenset(cfg.get("ops", ()))

    def quantize_inputs(self, op, ins, env):
        import jax.numpy as jnp

        fwd_cfg = self.cfg["inputs"]
        fmax = float(self.cfg["fwd_max"])
        out = {}
        for slot, vs in ins.items():
            names = op.input_names.get(slot, [])
            vals = []
            for i, v in enumerate(vs):
                n = names[i] if i < len(names) else None
                st = fwd_cfg.get(n)
                if st is None or st["scale"] not in env \
                        or not hasattr(v, "dtype") \
                        or not hasattr(v, "astype") \
                        or not jnp.issubdtype(v.dtype, jnp.floating):
                    vals.append(v)
                    continue
                scale = jnp.reshape(env[st["scale"]],
                                    ()).astype(jnp.float32)
                obs = n + _FP8_OBS_SUFFIX
                amax = jnp.max(jnp.abs(v.astype(jnp.float32)))
                prev = env.get(obs)
                env[obs] = amax if prev is None \
                    else jnp.maximum(prev, amax)
                vals.append(_fp8_qdq(v, scale, jnp.float8_e4m3fn, fmax))
            out[slot] = vals
        return out

    def wrap_outputs(self, op, env):
        import jax.numpy as jnp

        global _fp8_grad_qdq_site
        if _fp8_grad_qdq_site is None:
            _fp8_grad_qdq_site = _fp8_grad_qdq_site_make()
        grad_cfg = self.cfg["grads"]
        fmax = jnp.float32(self.cfg["grad_max"])
        for n in op.output_arg_names:
            st = grad_cfg.get(n)
            tap = n + _FP8_GTAP_SUFFIX
            if st is None or tap not in env or st["scale"] not in env:
                continue
            v = env[n]
            if not hasattr(v, "dtype") or \
                    not jnp.issubdtype(v.dtype, jnp.floating):
                continue
            gscale = jnp.reshape(env[st["scale"]],
                                 ()).astype(jnp.float32)
            env[n] = _fp8_grad_qdq_site(v, env[tap], gscale, fmax)


def _update_fp8_scaling(cfg, env, tap_grads, axis_names):
    """Post-step delayed-scaling state machine: roll each tensor's amax
    history with this step's observation (0 when the site never ran —
    e.g. dead branch), pmax'd over every LIVE mesh axis so the scale
    stays replica-uniform (TP members see different local shards; a
    per-member scale would make the next step's HLO diverge), and
    recompute scale = fmax / max(history) (1.0 while the window is
    empty). Runs unconditionally OUTSIDE any cond — like the loss-scale
    counters, state advances even on anomalous steps."""
    import jax
    import jax.numpy as jnp

    from ..parallel import env as penv

    axes = penv.active_axes() or {}
    live = [a for a in axis_names if a is not None and axes.get(a, 1) > 1]

    def step(st, amax, fmax):
        amax = jnp.reshape(jnp.asarray(amax, jnp.float32), ())
        for a in live:
            amax = jax.lax.pmax(amax, a)
        hist_n, scale_n = st["hist"], st["scale"]
        hist = env[hist_n].astype(jnp.float32).reshape(-1)
        hist = jnp.concatenate([amax[None], hist[:-1]])
        m = jnp.max(hist)
        scale = jnp.where(m > 0, jnp.float32(fmax) / m, jnp.float32(1.0))
        env[hist_n] = hist.reshape(env[hist_n].shape).astype(
            env[hist_n].dtype)
        env[scale_n] = jnp.reshape(scale, env[scale_n].shape).astype(
            env[scale_n].dtype)

    for n, st in cfg["inputs"].items():
        step(st, env.pop(n + _FP8_OBS_SUFFIX, 0.0), cfg["fwd_max"])
    for n, st in cfg["grads"].items():
        step(st, tap_grads.get(n + _FP8_GTAP_SUFFIX, 0.0),
             cfg["grad_max"])


def _host_callback_op(opdef, op, ins, attrs):
    """Lower a host-side (`no_jit`) op inside a jitted block via
    jax.pure_callback. Reference parity: CPU-only kernels (e.g.
    bipartite_match_op.cc) run on host mid-graph with device transfers
    inserted by PrepareData (operator.cc:1120); pure_callback is the XLA
    equivalent. Output shapes are probed by running the op once at trace
    time on zero-filled inputs — ops whose OUTPUT SHAPE depends on input
    values (multiclass_nms-style) cannot run under jit, same as any XLA
    program, and keep working eagerly. No gradient flows through the
    callback (host ops produce matches/indices, not differentiable
    values)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    slot_order = sorted(ins)
    flat = [v for s in slot_order for v in ins[s]]
    layout = [(s, len(ins[s])) for s in slot_order]

    def rebuild(flat_vals):
        d, i = {}, 0
        for s, n in layout:
            d[s] = list(flat_vals[i:i + n])
            i += n
        return d

    if opdef.infer_shape is not None:
        # side-effecting host ops (print, assert) declare their output
        # shapes so the zero-filled probe below — which would EXECUTE
        # the side effect at trace time — is never run for them
        spec_in = {s: [(tuple(v.shape), str(np.dtype(v.dtype)))
                       for v in vs] for s, vs in ins.items()}
        inferred = opdef.infer_shape(spec_in, dict(attrs))
        out_slots = [(s, len(vs)) for s, vs in sorted(inferred.items())]
        result_spec = [jax.ShapeDtypeStruct(tuple(shape), np.dtype(dt))
                       for _, vs in sorted(inferred.items())
                       for shape, dt in vs]
    else:
        probe = [np.zeros(v.shape, v.dtype) for v in flat]
        # NOTE: under stackless tracing, jnp constants created inside
        # compute come back as tracers — only .shape/.dtype may be read.
        probe_out = ops_lib.normalize_outs(
            opdef.compute(rebuild(probe), dict(attrs)))
        out_slots = [(s, len(vs)) for s, vs in sorted(probe_out.items())]
        result_spec = [
            jax.ShapeDtypeStruct(tuple(v.shape), np.dtype(v.dtype))
            for _, vs in sorted(probe_out.items()) for v in vs]

    def host_fn(*flat_vals):
        outs = ops_lib.normalize_outs(opdef.compute(
            rebuild([np.asarray(v) for v in flat_vals]), dict(attrs)))
        return tuple(np.asarray(v) for _, vs in sorted(outs.items())
                     for v in vs)

    if op.type in ("print", "assert"):
        # observable effects with passthrough-or-no outputs: a debug
        # callback keeps the effect alive under jit AND autodiff
        # (pure_callback with unused outputs is DCE-able; io_callback
        # does not support vjp), and the outputs are synthesized as the
        # identity of the inputs instead of round-tripping to host
        def effect_fn(*flat_vals):
            opdef.compute(
                rebuild([np.asarray(v) for v in flat_vals]),
                dict(attrs))

        jax.debug.callback(effect_fn, *flat, ordered=True)
        outs = {}
        for s, n in out_slots:
            outs[s] = list(flat[:n])  # print: Out = its input
        return outs
    flat_out = jax.pure_callback(host_fn, tuple(result_spec), *flat)
    outs, i = {}, 0
    for s, n in out_slots:
        outs[s] = [jnp.asarray(v) for v in flat_out[i:i + n]]
        i += n
    return outs


def _run_ops(ops, env, key0, base_idx=0, amp_lists=None):
    for i, op in enumerate(ops):
        _exec_op(op, env, key0, base_idx + i, amp_lists=amp_lists)


# -- control-flow lowering (reference: operators/controlflow/while_op.cc:42,
# conditional_block_op.cc -> lax.while_loop / lax.cond / lax.switch;
# SURVEY.md §7 hard part (b): scope mutation becomes an explicit carry) --

def _sub_block_carry(sub_block, env):
    """Loop carry = sub-block writes that pre-exist in the enclosing env
    (paddle requires loop vars be created+initialized before the While).
    Includes writes made in NESTED control flow (a cond inside the while
    body assigning a loop var). Writes to loop-local temps are not
    carried."""
    carry, seen = [], set()
    for sop in sub_block.ops:
        for n in _op_reads_writes(sop)[1]:
            if n in env and n not in seen:
                carry.append(n)
                seen.add(n)
    return carry


def _exec_while(op, env, key0, op_idx, amp_lists):
    import jax
    import jax.numpy as jnp
    from jax import lax

    prog = op.block.program
    sub = prog.block(op.attrs["sub_block"])
    cond_name = op.attrs["cond_name"]
    carry_names = _sub_block_carry(sub, env)
    if cond_name not in carry_names:
        raise RuntimeError(
            "while: the loop body never rebinds condition var %r — the "
            "loop would not terminate" % cond_name)
    base_key = jax.random.fold_in(key0, op_idx)
    cond_pos = carry_names.index(cond_name)

    def cond_f(carry):
        return jnp.all(carry[1 + cond_pos])

    def body_f(carry):
        it = carry[0]
        e = dict(env)
        e.update(zip(carry_names, carry[1:]))
        # per-iteration rng so dropout etc. differs across iterations
        _run_ops(sub.ops, e, jax.random.fold_in(base_key, it),
                 amp_lists=amp_lists)
        return (it + 1,) + tuple(e[n] for n in carry_names)

    init = (jnp.int32(0),) + tuple(env[n] for n in carry_names)
    final = lax.while_loop(cond_f, body_f, init)
    env.update(zip(carry_names, final[1:]))


def _exec_scan(op, env, key0, op_idx, amp_lists):
    """`scan` op -> jax.lax.scan: fixed-trip loop whose body is traced
    and compiled ONCE regardless of depth — the TPU-native way to build
    deep identical-layer stacks (12-layer BERT encoder: one body in the
    HLO instead of 12 clones). Carry contract is the While contract
    (sub-block writes to pre-existing vars are threaded functionally);
    per-iteration slices of the stacked inputs arrive as scan xs; with
    attrs['remat'] the body is wrapped in jax.checkpoint, giving
    activation recompute per layer without RecomputeOptimizer's
    segment machinery. Reverse-mode grads fall out of the ordinary
    jax.vjp over lax.scan (no recurrent_grad op — contrast
    reference recurrent_op.cc's scope-mutation step loop)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    prog = op.block.program
    sub = prog.block(op.attrs["sub_block"])
    n = int(op.attrs["n"])
    xs_stacked = list(op.attrs.get("xs_stacked", []))
    xs_slice = list(op.attrs.get("xs_slice", []))
    carry_names = _sub_block_carry(sub, env)
    if not carry_names:
        raise RuntimeError(
            "scan: the body never rebinds a pre-existing var — every "
            "iteration's results would be discarded. Rebind the carry "
            "with layers.assign(new_val, output=carried_var).")
    base_key = jax.random.fold_in(key0, op_idx)

    iter_name = op.attrs.get("iter_var") or None

    def body(carry, xs):
        it = carry[0]
        e = dict(env)
        e.update(zip(carry_names, carry[1:]))
        e.update(zip(xs_slice, xs))
        if iter_name:
            e[iter_name] = jnp.reshape(it, (1,)).astype(jnp.int32)
        # per-iteration rng so dropout masks differ across layers
        _run_ops(sub.ops, e, jax.random.fold_in(base_key, it),
                 amp_lists=amp_lists)
        return ((it + 1,) + tuple(e[nm] for nm in carry_names)), None

    if op.attrs.get("remat"):
        body = jax.checkpoint(body)
    init = (jnp.int32(0),) + tuple(env[nm] for nm in carry_names)
    xs = tuple(env[nm] for nm in xs_stacked)
    final, _ = lax.scan(body, init, xs, length=n)
    env.update(zip(carry_names, final[1:]))


def _branch_out_names(op, env, blocks):
    """Names a branch op must return: its declared outputs PLUS any writes
    (incl. nested) to vars that pre-exist in env — so a branch assigning
    an outer var (e.g. a loop var from an enclosing While) propagates.
    Branches that don't write a given name return env's value unchanged,
    keeping lax.cond/switch branch signatures identical."""
    names = list(op.attrs["out_names"])
    seen = set(names)
    for blk in blocks:
        for sop in blk.ops:
            for n in _op_reads_writes(sop)[1]:
                if n in env and n not in seen:
                    names.append(n)
                    seen.add(n)
    return names


def _branch_fn(block, env, key, out_names, amp_lists):
    def f(_):
        e = dict(env)
        _run_ops(block.ops, e, key, amp_lists=amp_lists)
        return tuple(e[n] for n in out_names)

    return f


def _exec_cond(op, env, key0, op_idx, amp_lists):
    import jax
    import jax.numpy as jnp
    from jax import lax

    prog = op.block.program
    blk_t = prog.block(op.attrs["sub_block_t"])
    blk_f = prog.block(op.attrs["sub_block_f"])
    out_names = _branch_out_names(op, env, [blk_t, blk_f])
    pred = jnp.all(env[op.attrs["cond_name"]])
    key = jax.random.fold_in(key0, op_idx)
    outs = lax.cond(
        pred,
        _branch_fn(blk_t, env, key, out_names, amp_lists),
        _branch_fn(blk_f, env, key, out_names, amp_lists),
        None)
    env.update(zip(out_names, outs))


def _exec_conditional_block(op, env, key0, op_idx, amp_lists):
    """Reference conditional_block_op.cc: run the sub-block iff Cond is
    true. Functional form: lax.cond whose false branch returns the
    enclosing env's values unchanged, so every carried write must
    pre-exist (the same contract as While loop vars)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    prog = op.block.program
    blk = prog.block(op.attrs["sub_block"])
    out_names = _branch_out_names(op, env, [blk]) \
        if "out_names" in op.attrs else _sub_block_carry(blk, env)
    cond_vals = [env[n] for n in op.input_names.get("Cond", [])]
    pred = jnp.all(cond_vals[0]) if cond_vals else jnp.bool_(True)
    key = jax.random.fold_in(key0, op_idx)
    true_fn = _branch_fn(blk, env, key, out_names, amp_lists)
    # outputs first created INSIDE the block (no pre-existing env value)
    # are zeros on the skip path, like an unexecuted reference scope
    shapes = jax.eval_shape(true_fn, None)

    def false_fn(_):
        return tuple(
            env[n] if n in env else jnp.zeros(s.shape, s.dtype)
            for n, s in zip(out_names, shapes))

    outs = lax.cond(pred, true_fn, false_fn, None)
    env.update(zip(out_names, outs))


def _exec_switch_case(op, env, key0, op_idx, amp_lists):
    import jax
    import jax.numpy as jnp
    from jax import lax

    prog = op.block.program
    keys = op.attrs["keys"]
    blocks = [prog.block(b) for b in op.attrs["sub_blocks"]]  # default last
    out_names = _branch_out_names(op, env, blocks)
    key = jax.random.fold_in(key0, op_idx)
    idx_val = jnp.reshape(env[op.attrs["index_name"]], ()).astype(jnp.int32)
    # map the user's branch keys to positions; no match -> default (last)
    sel = jnp.full((), len(blocks) - 1, jnp.int32)
    for pos, k in enumerate(keys):
        sel = jnp.where(idx_val == k, jnp.int32(pos), sel)
    fns = [_branch_fn(blk, env, key, out_names, amp_lists)
           for blk in blocks]
    outs = lax.switch(sel, fns, None)
    env.update(zip(out_names, outs))


def _run_gradient_merge(ops, bwd_idx, gm, env, key0, amp_lists,
                        sync_fn=None, shard_plan=None, block=None):
    """k-step gradient accumulation (reference: gradient_merge strategy,
    `framework/ir/multi_batch_merge_pass.cc` / fleet 2.0 GradientMerge
    meta-optimizer). Each step adds the fresh grads into persistable
    accumulators; the optimizer section runs under lax.cond only on every
    k-th step (with the averaged accumulated grads), then the
    accumulators reset to zero. Off steps leave params/moments untouched.

    With a `shard_plan` (ZeRO-1 + gradient merge), the once-per-k sync
    on the MERGED grads is a (bucketed) reduce-scatter instead of an
    allreduce, and the post section inside the cond runs on flat 1/N
    shards — the merged-grad update path is sharded too. Sharded
    optimizer state is a ShardVal on BOTH branches (skip passes the
    incoming shard through), so the cond's pytrees agree; any other
    shard-space value is gathered back to its replicated form before
    leaving the branch."""
    import jax.numpy as jnp
    from jax import lax

    if shard_plan is not None:
        from ..parallel import sharded_update as _su

    k = int(gm["k_steps"])
    avg = bool(gm.get("avg", True))
    acc_map = dict(gm["acc_map"])  # grad name -> accumulator name
    counter_n = gm["counter"]
    post_ops = ops[bwd_idx + 1:]

    cnt = jnp.reshape(env[counter_n], ()).astype(jnp.int32)
    new_cnt = cnt + 1
    do_apply = (new_cnt % k) == 0
    for g, acc in acc_map.items():
        env[acc] = env[acc] + env[g].astype(env[acc].dtype)

    # cond-uniform outputs: post-section writes that pre-exist in env
    # (param/moment/lr updates), plus the accumulators
    out_names, seen = [], set()
    for op in post_ops:
        for n in _op_reads_writes(op)[1]:
            if n in env and n not in seen:
                out_names.append(n)
                seen.add(n)
    out_names.extend(a for a in acc_map.values() if a not in seen)

    def apply_branch(_):
        e = dict(env)
        if shard_plan is not None:
            # sharded merged-grad sync: reduce-scatter (per-bucket when
            # FLAGS_tpu_comm_bucket_mb > 0) ONCE per k steps — the
            # predicate is counter-driven, so every shard takes this
            # branch together and the collectives stay uniform
            gdict = {g: (e[acc] / k if avg else e[acc])
                     for g, acc in acc_map.items()
                     if g in shard_plan.grad_names}
            scattered = _su.bucketed_reduce_scatter(
                gdict, shard_plan, mean=True)
            for g, acc in acc_map.items():
                if g in scattered:
                    e[g] = scattered[g].astype(e[g].dtype)
                else:
                    merged = e[acc] / k if avg else e[acc]
                    if sync_fn is not None:
                        merged = sync_fn(merged, g)
                    e[g] = merged.astype(e[g].dtype)
            _su.run_sharded_post_ops(post_ops, e, key0, bwd_idx + 1,
                                     amp_lists, shard_plan, block)
        else:
            for g, acc in acc_map.items():
                merged = e[acc] / k if avg else e[acc]
                if sync_fn is not None:
                    # implicit-DP sync on the merged grad: one allreduce
                    # per k steps (the predicate is counter-driven, so
                    # every shard takes this branch together)
                    merged = sync_fn(merged, g)
                e[g] = merged.astype(e[g].dtype)
            _run_ops(post_ops, e, key0, base_idx=bwd_idx + 1,
                     amp_lists=amp_lists)
        for acc in acc_map.values():
            e[acc] = jnp.zeros_like(e[acc])
        if shard_plan is not None:
            # branch-exit normalization: sharded state stays a ShardVal
            # (the skip branch passes the incoming shard through, so
            # pytrees agree); every other shard-space value gathers back
            return tuple(
                (_su.gather_full(e[n], shard_plan, name=n)
                 if isinstance(e[n], _su.ShardVal)
                 and n not in shard_plan.sharded_state else e[n])
                for n in out_names)
        return tuple(e[n] for n in out_names)

    def skip_branch(_):
        return tuple(env[n] for n in out_names)

    outs = lax.cond(do_apply, apply_branch, skip_branch, None)
    env.update(zip(out_names, outs))
    env[counter_n] = jnp.reshape(new_cnt % k,
                                 env[counter_n].shape).astype(
                                     env[counter_n].dtype)


def _amp_found_inf(grads, axis_names):
    """Global non-finite indicator over this step's (synced) gradients.
    Counted on each replica's LOCAL values — under ZeRO the 1/N shard
    vecs, 1/N the work of a full-tensor scan — then psum'd over the dp
    axis/axes when live: the `lax.cond` that skips the weight update
    must see a replica-UNIFORM predicate (an overflow lands in exactly
    one replica's shard slots; without the psum the other replicas
    would run the update branch and its all-gathers alone — deadlock).
    On a hybrid mesh `axis_names` is the (ici, dcn) pair: the count
    psums over both so every pod agrees."""
    import jax.numpy as jnp

    from ..parallel import env as penv
    from ..parallel import sharded_update as _su

    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    total = jnp.zeros((), jnp.float32)
    for g in grads.values():
        v = g.vec if isinstance(g, _su.ShardVal) else g
        total = total + jnp.sum(
            (~jnp.isfinite(v.astype(jnp.float32))).astype(jnp.float32))
    axes = penv.active_axes() or {}
    from ..observability import attribution as _attr

    with _attr.marker_scope(_attr.amp_marker("found_inf")):
        for axis_name in axis_names:
            if axis_name is not None and axes.get(axis_name, 1) > 1:
                import jax

                total = jax.lax.psum(total, axis_name)
    return total > 0


def _amp_unscale(g, scale):
    """grad / loss_scale, computed in fp32 (an fp16 division would
    re-lose the low bits the scaling protected) then cast back."""
    import jax.numpy as jnp

    from ..parallel import sharded_update as _su

    if isinstance(g, _su.ShardVal):
        return _su.ShardVal(_amp_unscale(g.vec, scale), g.shape)
    return (g.astype(jnp.float32) / scale).astype(g.dtype)


def _run_loss_scaled_post(ops, bwd_idx, dls, env, key0, amp_lists,
                          shard_plan, block, found_inf,
                          fetch_names=()):
    """fp16 dynamic loss scaling (reference: decorator.py's
    amp_check_finite_and_scale + update_loss_scaling op pair). The whole
    post-backward section — optimizer update, clip, lr schedule —
    runs under ``lax.cond`` on the psum'd finite check: an overflow step
    leaves params/moments/counters untouched (the reference's
    found_inf short-circuit inside each optimizer kernel). The scale
    state machine updates OUTSIDE the cond with plain arithmetic:

      clean step:    good += 1; good == incr_every_n_steps
                     -> scale *= incr_ratio, good = 0
      overflow step: bad += 1, good = 0; bad == decr_every_n_nan_or_inf
                     -> scale *= decr_ratio, bad = 0

    ZeRO interplay mirrors _run_gradient_merge's branch normalization:
    values that are ShardVals on BOTH sides (sharded opt state / fp32
    masters, and the scattered grads themselves — their shards pass
    through, honoring the ZeRO-2 lifetime) stay sharded; a value the
    apply branch shards but the skip branch holds full (an updated
    param not covered by the deferred per-bucket gathers) gathers at
    branch exit so the cond's pytrees agree."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..parallel import sharded_update as _su

    post_ops = ops[bwd_idx + 1:]
    out_names, seen = [], set()
    post_writes = set()
    for op in post_ops:
        for n in _op_reads_writes(op)[1]:
            post_writes.add(n)
            if n in env and n not in seen:
                out_names.append(n)
                seen.add(n)
    # post-CREATED vars that are fetched (a regularizer term, the
    # global grad norm): they exist only inside the branch, so they
    # must ride the cond outputs or the fetch loop never sees them —
    # on a skipped (overflow) step they read as zeros, like an
    # unexecuted reference scope (the conditional_block contract)
    created = [n for n in fetch_names
               if n in post_writes and n not in env and n not in seen]
    out_names.extend(created)

    def _norm(n, v):
        """Align a branch output with the skip side's type: the apply
        branch may promote a rebound var's dtype (fp16 grad * fp32
        clip scale -> fp32) — the cond's pytrees must agree, and the
        optimizer already consumed the full-precision value INSIDE the
        branch, so the exit cast costs no update precision."""
        ref = env.get(n)
        if isinstance(v, _su.ShardVal):
            if shard_plan is not None and \
                    not isinstance(ref, _su.ShardVal):
                v = _su.gather_full(v, shard_plan, name=n)
            elif isinstance(ref, _su.ShardVal):
                return v.astype(ref.dtype) \
                    if v.dtype != ref.dtype else v
        if ref is not None and hasattr(ref, "dtype") \
                and hasattr(v, "astype") and v.dtype != ref.dtype:
            v = v.astype(ref.dtype)
        return v

    def apply_branch(_):
        e = dict(env)
        if shard_plan is not None:
            _su.run_sharded_post_ops(post_ops, e, key0, bwd_idx + 1,
                                     amp_lists, shard_plan, block)
        else:
            _run_ops(post_ops, e, key0, base_idx=bwd_idx + 1,
                     amp_lists=amp_lists)
        return tuple(_norm(n, e[n]) for n in out_names)

    shapes = jax.eval_shape(apply_branch, None) if created else None

    def skip_branch(_):
        return tuple(
            env[n] if n in env
            else jnp.zeros(shapes[i].shape, shapes[i].dtype)
            for i, n in enumerate(out_names))

    outs = lax.cond(found_inf, skip_branch, apply_branch, None)
    env.update(zip(out_names, outs))

    scale_n, good_n, bad_n = dls["scale"], dls["good"], dls["bad"]
    scale = jnp.reshape(env[scale_n], ()).astype(jnp.float32)
    good = jnp.reshape(env[good_n], ()).astype(jnp.int32)
    bad = jnp.reshape(env[bad_n], ()).astype(jnp.int32)
    new_good = jnp.where(found_inf, 0, good + 1)
    new_bad = jnp.where(found_inf, bad + 1, 0)
    grow = jnp.logical_and(
        jnp.logical_not(found_inf),
        new_good >= int(dls.get("incr_every_n_steps", 1000)))
    shrink = jnp.logical_and(
        found_inf,
        new_bad >= int(dls.get("decr_every_n_nan_or_inf", 2)))
    new_scale = jnp.where(
        shrink, scale * jnp.float32(dls.get("decr_ratio", 0.8)),
        jnp.where(grow, scale * jnp.float32(dls.get("incr_ratio", 2.0)),
                  scale))
    new_good = jnp.where(grow, 0, new_good)
    new_bad = jnp.where(shrink, 0, new_bad)
    for name, val in ((scale_n, new_scale), (good_n, new_good),
                      (bad_n, new_bad)):
        env[name] = jnp.reshape(val, env[name].shape).astype(
            env[name].dtype)


def _split_at_checkpoints(ops, ckpt_names):
    """Segment boundaries for activation recompute: a segment ends right
    after the (last) op that writes each checkpoint variable. Returns a
    list of (start, stop) index pairs covering `ops`."""
    cuts = set()
    for cn in ckpt_names:
        last = None
        for i, op in enumerate(ops):
            if cn in op.output_arg_names:
                last = i
        if last is not None and last + 1 < len(ops):
            cuts.add(last + 1)
    bounds, prev = [], 0
    for c in sorted(cuts):
        bounds.append((prev, c))
        prev = c
    bounds.append((prev, len(ops)))
    return bounds


def _remat_segments(fwd_ops, ckpt_names, live_out):
    """Plan jax.checkpoint segments (reference: backward.py:629 recompute
    segments + optimizer.py:4485 RecomputeOptimizer). Each entry is
    (start, stop, needed_after): `needed_after` is the set of names still
    read by later forward segments or by anything downstream (loss, post-
    backward ops, fetches, state outputs) — the only values a checkpointed
    segment must emit, so XLA stores just the boundary residuals and
    rematerializes segment interiors during the backward pass."""
    bounds = _split_at_checkpoints(fwd_ops, ckpt_names)
    if len(bounds) <= 1:
        return None
    out = []
    needed = set(live_out)
    for start, stop in reversed(bounds):
        out.append((start, stop, frozenset(needed)))
        for op in fwd_ops[start:stop]:
            needed.update(_op_reads_writes(op)[0])
    out.reverse()
    return out


def _diffable(block, name, env):
    v = block._find_var_recursive(name)
    if v is None or v.stop_gradient:
        return False
    import jax.numpy as jnp

    val = env.get(name)
    return val is not None and jnp.issubdtype(
        np.asarray(val).dtype if not hasattr(val, "dtype") else val.dtype,
        jnp.floating)


def build_block_fn(program, block, feed_names, fetch_names,
                   state_in, state_out, shard_plan=None,
                   sparse_plan=None, tp_plan=None):
    """Build the pure python fn to be jitted. With `shard_plan` (a
    parallel.sharded_update.ShardedUpdatePlan; only under _compile_dp),
    optimizer-bound gradients are reduce-scattered instead of pmean'd,
    the post-backward section runs on flat 1/N shards, and updated
    params are all-gathered back — ZeRO-1 weight-update sharding.

    With `sparse_plan` (an embedding.SparseTablePlan), vocab-sharded
    tables arrive as row shards, lookups lower through the sparse
    engine, and each table's gradient is collected via a zero "tap"
    diff var (the table itself never enters jax.vjp — no dense
    vocab-sized cotangent exists) and applied as a row-sparse update
    on the owning shard.

    With `tp_plan` (a parallel.tensor_parallel.TensorParallelPlan),
    model-sharded weights arrive as local blocks, their consuming ops
    lower through the TP engine's collectives on the `model` axis, and
    grad sync stays on the (dcn, replica) data axes — model members
    hold DISTINCT weight shards whose grads must never be averaged
    over `model`, while devices agreeing on the model coordinate hold
    the SAME shard, which is exactly the group the (dcn, ici)
    pmean/reduce-scatter already syncs."""
    import jax
    import jax.numpy as jnp

    if shard_plan is not None:
        from ..parallel import sharded_update as _su
    else:
        _su = None
    if sparse_plan is not None:
        from ..embedding import engine as _emb
    else:
        _emb = None
    if tp_plan is not None:
        from ..parallel import tensor_parallel as _tp
    else:
        _tp = None

    ops = list(block.ops)
    bwd_indices = [i for i, op in enumerate(ops) if op.type == "backward"]
    if len(bwd_indices) > 1:
        raise NotImplementedError("multiple backward sections in one block")
    bwd_idx = bwd_indices[0] if bwd_indices else None
    amp_lists = _amp_trace_policy(program)
    # Implicit DP grad sync (reference: multi_devices_graph_pass.cc:464
    # inserts an AllReduceOpHandle per gradient for ParallelExecutor).
    # The fleet transpiler emits explicit c_allreduce ops ON THE GRAD
    # VARS after backward instead — when those are present the program
    # owns its own sync and pmean-ing here would double-reduce. Only
    # grad-consuming allreduces count: a forward collective (e.g. a
    # globally averaged metric) must not disable the sync.
    _post_ops = ops[bwd_idx + 1:] if bwd_idx is not None else []
    _has_explicit_sync = any(
        (op.type.startswith("c_allreduce") or op.type == "allreduce")
        and any(n.endswith("@GRAD") for n in op.input_arg_names)
        for op in _post_ops)
    _implicit_dp = getattr(program, "_data_parallel", False) \
        and not _has_explicit_sync
    _dp_axis_name = getattr(program, "_dp_axis", "dp")
    # hybrid (dcn, ici) mesh: _dp_axis is the intra-pod ici axis and
    # _dcn_axis the cross-pod one; a full-tensor sync lowers
    # hierarchically (psum over ici, then the pod partials over dcn)
    # so its association matches the scatter path's — the pairing that
    # keeps the sharded update bit-identical to this reference
    _dcn_axis_name = getattr(program, "_dcn_axis", None)
    # tensor parallelism: the model axis never joins the grad sync, but
    # the AMP found_inf predicate must still psum over it — model
    # members hold DIFFERENT grad shards, and a lax.cond predicate that
    # differs across mesh members would deadlock the collectives inside
    _model_axis_name = tp_plan.model_axis if tp_plan is not None else None

    def _dp_sync_axes():
        from ..parallel import env as penv

        axes = penv.active_axes() or {}
        return tuple(a for a in (_dp_axis_name, _dcn_axis_name)
                     if a is not None and axes.get(a, 1) > 1)

    def _dp_pmean(g, name=None):
        """pmean over the dp axis when implicit sync is on and the axis
        is live (inside shard_map); identity otherwise. On a hybrid
        mesh: hierarchical psum (ici, then dcn) / world. `name` stamps
        the emitted collective with a grad-sync provenance marker so
        the census maps it back to its gradient."""
        if not _implicit_dp:
            return g
        live = _dp_sync_axes()
        if not live:
            return g
        import jax as _jax

        from ..observability import attribution as _attr

        with _attr.marker_scope(_attr.grad_sync_marker(name)) \
                if name else contextlib.nullcontext():
            if _dcn_axis_name is None:
                # flat dp: keep the exact pre-hybrid lowering
                return _jax.lax.pmean(g, _dp_axis_name)
            from ..parallel import env as penv

            axes = penv.active_axes() or {}
            total = g
            world = 1
            for a in live:
                total = _jax.lax.psum(total, a)
                world *= axes[a]
            return total / world


    def fn(feeds: Dict, states_mut: Dict, states_ro: Dict, seed):
        if sparse_plan is None and tp_plan is None:
            return _fn_body(feeds, states_mut, states_ro, seed)
        # install the sparse/TP plans for this trace (contextvars — the
        # engines' per-op routing in _exec_op_stamped reads them; safe
        # under concurrent background-warmup traces)
        with contextlib.ExitStack() as stack:
            if sparse_plan is not None:
                stack.enter_context(_emb.active_plan(sparse_plan))
            if tp_plan is not None:
                stack.enter_context(_tp.active_plan(tp_plan))
            return _fn_body(feeds, states_mut, states_ro, seed)

    def _fn_body(feeds: Dict, states_mut: Dict, states_ro: Dict, seed):
        env = {}
        env.update(states_ro)
        env.update(states_mut)
        env.update(feeds)
        key0 = make_key(seed)
        if shard_plan is not None:
            # sharded optimizer state arrives as raw (padded/N,) vecs
            # from shard_map; wrap with the logical shapes
            _su.wrap_sharded_state(env, shard_plan)
        if sparse_plan is not None:
            # vocab-sharded tables + per-row moments arrive as raw
            # local (rows/N, dim) blocks from shard_map; wrap them
            _emb.wrap_tables(env, sparse_plan)

        if bwd_idx is None:
            _run_ops(ops, env, key0, amp_lists=amp_lists)
        else:
            fwd_ops = ops[:bwd_idx]
            bop = ops[bwd_idx]
            loss_name = bop.attrs["loss_name"]
            requested = bop.attrs.get("diff_names", [])
            loss_scale = bop.attrs.get("loss_scale", 1.0)
            gm = bop.attrs.get("gradient_merge")
            # fp16 loss scaling: dynamic (scale state machine under
            # lax.cond) or static (constant factor, no skip). The
            # merged-grad cond owns the cadence under gradient merge,
            # so dls never combines with it (decorator warns).
            dls = bop.attrs.get("dynamic_loss_scaling") \
                if gm is None else None
            static_ls = bop.attrs.get("static_loss_scaling") \
                if gm is None else None
            if (dls is not None or static_ls) and _has_explicit_sync:
                # explicit-sync (fleet-transpiled) programs sum grads
                # via c_allreduce_sum ops INSIDE the post section: the
                # finite check here would see pre-sum local values
                # (overflow introduced by the N-way fp16 sum escapes
                # the skip-cond) and the unscale — dynamic OR static —
                # would flush small grads back to zero before the sum,
                # the protection inverted. Disable rather than
                # mis-protect; say so loudly once (the dynamic scale
                # state then passes through each step unchanged).
                import warnings

                warnings.warn(
                    "fp16 loss scaling is not wired for explicit-sync "
                    "(fleet-transpiled) gradient programs; training "
                    "proceeds UNSCALED — expect fp16 gradient "
                    "underflow. Use bfloat16 (no scaling needed) or "
                    "implicit DP sync.")
                dls = None
                static_ls = None
            tap_names = frozenset()
            if sparse_plan is not None:
                # vocab-sharded tables never enter vjp: their grads
                # arrive through the lookup-output taps instead (no
                # dense vocab-sized cotangent is ever built)
                requested = [n for n in requested
                             if n not in sparse_plan.tables]
            diff_names = [n for n in requested
                          if n in env and _diffable(block, n, env)]
            if sparse_plan is not None:
                taps = _emb.tap_specs(sparse_plan, env)
                env.update(taps)
                tap_names = frozenset(taps)
                diff_names = diff_names + sorted(taps)
            # fp8 tier: one synthetic scalar diff var per fp8 op output
            # — its vjp cotangent carries amax(|dY|) out of the
            # backward (the sparse-tap idiom; a site consumed twice
            # sums, a conservative upper bound on the true amax)
            fp8_cfg = bop.attrs.get("fp8_delayed_scaling")
            fp8_tap_names = frozenset()
            if fp8_cfg is not None:
                fp8_taps = {o + _FP8_GTAP_SUFFIX:
                            jnp.zeros((), jnp.float32)
                            for o in fp8_cfg["grads"]}
                env.update(fp8_taps)
                fp8_tap_names = frozenset(fp8_taps)
                diff_names = diff_names + sorted(fp8_taps)

            ckpt_names = list(bop.attrs.get("checkpoints", []) or [])
            segments = None
            if ckpt_names:
                live_out = set(fetch_names) | set(state_out) | {loss_name}
                for post_op in ops[bwd_idx + 1:]:
                    live_out.update(_op_reads_writes(post_op)[0])
                if fp8_cfg is not None:
                    # fwd amax observations must survive the remat
                    # segment boundaries to reach the vjp aux env
                    live_out.update(n + _FP8_OBS_SUFFIX
                                    for n in fp8_cfg["inputs"])
                segments = _remat_segments(fwd_ops, ckpt_names, live_out)

            def fseg(dvars):
                e = dict(env)
                e.update(dvars)
                if segments is None:
                    _run_ops(fwd_ops, e, key0, amp_lists=amp_lists)
                else:
                    for start, stop, needed in segments:
                        def seg_fn(carry, _ops=fwd_ops[start:stop],
                                   _start=start, _needed=needed):
                            ee = dict(carry)
                            _run_ops(_ops, ee, key0, base_idx=_start,
                                     amp_lists=amp_lists)
                            return {n: ee[n] for n in _needed if n in ee}

                        e.update(jax.checkpoint(seg_fn)(e))
                loss_sum = jnp.sum(e[loss_name].astype(jnp.float32))
                return loss_sum, e

            diff_in = {n: env[n] for n in diff_names}
            # the fp8 qdq sites are live ONLY inside this vjp region:
            # forward trace AND the backward replay (remat re-traces
            # segments under vjp_fn and must reproduce the exact same
            # computation) — post-backward ops never quantize
            with (_fp8_trace_scope(_Fp8Trace(fp8_cfg))
                  if fp8_cfg is not None else contextlib.nullcontext()):
                _, vjp_fn, env_after = jax.vjp(fseg, diff_in,
                                               has_aux=True)
                ct = jnp.asarray(loss_scale, jnp.float32)
                amp_scale = None
                if dls is not None:
                    # scale the cotangent by the LIVE scale state so
                    # fp16 backward intermediates stay representable
                    amp_scale = jnp.reshape(env[dls["scale"]],
                                            ()).astype(jnp.float32)
                    ct = ct * amp_scale
                elif static_ls:
                    amp_scale = jnp.asarray(static_ls, jnp.float32)
                    ct = ct * amp_scale
                grads = vjp_fn(ct)[0]
            env = dict(env_after)
            tap_grads = {}
            if sparse_plan is not None:
                # tap cotangents stay LOCAL (per-replica batch slice):
                # the cross-replica combine happens inside the sparse
                # engine's gathered scatter-add, never via pmean
                tap_grads = {n: grads.pop(n) for n in list(grads)
                             if n in tap_names}
            fp8_tap_grads = {}
            if fp8_cfg is not None:
                # grad-amax observations: popped BEFORE the grad sync
                # (the delayed-scaling update pmax's them itself)
                fp8_tap_grads = {n: grads.pop(n) for n in list(grads)
                                 if n in fp8_tap_names}
            if gm is None:
                if shard_plan is not None and _implicit_dp:
                    if shard_plan.buckets:
                        # bucketed, backward-ordered collectives
                        # (FLAGS_tpu_comm_bucket_mb): one psum_scatter
                        # per bucket, each depending only on its own
                        # grads — XLA's latency-hiding scheduler can
                        # start early buckets' ring transfers while the
                        # rest of the backward still computes
                        gnames = {n: framework.grad_var_name(n)
                                  for n in grads}
                        gdict = {gn: grads[n]
                                 for n, gn in gnames.items()
                                 if gn in shard_plan.grad_names}
                        scattered = _su.bucketed_reduce_scatter(
                            gdict, shard_plan, mean=True)
                        grads = {
                            n: (scattered[gn] if gn in scattered
                                else _dp_pmean(grads[n], gn))
                            for n, gn in gnames.items()}
                    else:
                        # ZeRO-1 per-variable collectives (the exact
                        # FLAGS_tpu_comm_bucket_mb=0 lowering):
                        # optimizer-bound grads reduce-scattered (pmean
                        # semantics -> /N); everything else keeps the
                        # replicated pmean (e.g. a fetched grad)
                        grads = {
                            n: (_su.reduce_scatter_mean(
                                g, shard_plan,
                                name=framework.grad_var_name(n))
                                if framework.grad_var_name(n)
                                in shard_plan.grad_names
                                else _dp_pmean(
                                    g, framework.grad_var_name(n)))
                            for n, g in grads.items()}
                else:
                    grads = {n: _dp_pmean(g, framework.grad_var_name(n))
                             for n, g in grads.items()}
            # dynamic loss scaling: the finite check runs on the SYNCED
            # (scattered) values each replica will actually consume,
            # psum'd over the dp axis so the update-skip predicate is
            # replica-uniform (a collective inside a divergent cond
            # would deadlock the mesh)
            found_inf = None
            if dls is not None:
                found_inf = _amp_found_inf(
                    {n: grads[n] for n in diff_names if n in grads},
                    (_dp_axis_name, _dcn_axis_name, _model_axis_name))
            # under gradient merge, sync once on the MERGED grads at the
            # k-step boundary instead of k per-micro-step allreduces
            from ..observability import attribution as _attr

            for n in diff_names:
                if n in tap_names or n in fp8_tap_names:
                    continue  # tap cotangents feed the engines
                gn = framework.grad_var_name(n)
                # stamp the grad post-processing (unscale + dtype cast)
                # with the gradient's provenance so its converts blame
                # the right var in the attribution report
                with _attr.marker_scope(_attr.grad_sync_marker(gn)):
                    g = grads[n]
                    if amp_scale is not None:
                        g = _amp_unscale(g, amp_scale)
                    env[gn] = g.astype(env[n].dtype)
            if sparse_plan is not None:
                # one SelectedRows-form gradient per table: site
                # (ids, dOut) pairs gathered over the data axes —
                # collective bytes proportional to touched rows
                _emb.install_sparse_grads(env, tap_grads, sparse_plan)
            loss_val = env[loss_name]
            env[framework.grad_var_name(loss_name)] = jnp.full(
                loss_val.shape, loss_scale, loss_val.dtype)
            if gm is None:
                if dls is not None:
                    _run_loss_scaled_post(ops, bwd_idx, dls, env, key0,
                                          amp_lists, shard_plan, block,
                                          found_inf,
                                          fetch_names=fetch_names)
                elif shard_plan is not None:
                    _su.run_sharded_post_ops(
                        ops[bwd_idx + 1:], env, key0, bwd_idx + 1,
                        amp_lists, shard_plan, block)
                else:
                    _run_ops(ops[bwd_idx + 1:], env, key0,
                             base_idx=bwd_idx + 1, amp_lists=amp_lists)
            else:
                _run_gradient_merge(ops, bwd_idx, gm, env, key0,
                                    amp_lists, sync_fn=_dp_pmean,
                                    shard_plan=shard_plan, block=block)
            if fp8_cfg is not None:
                # roll the delayed-scaling state AFTER the update (the
                # scales this step consumed came from previous steps'
                # histories — that is what makes the scaling "delayed")
                _update_fp8_scaling(
                    fp8_cfg, env, fp8_tap_grads,
                    (_dp_axis_name, _dcn_axis_name, _model_axis_name))

        fetches = []
        for n in fetch_names:
            if n not in env:
                raise RuntimeError("fetch var %r was never computed" % n)
            v = env[n]
            if shard_plan is not None and isinstance(v, _su.ShardVal):
                # fetched as full
                v = _su.gather_full(v, shard_plan, name=n)
            if sparse_plan is not None:
                if isinstance(v, _emb.TableShard):
                    # fetched tables gather back to the logical shape
                    v = _emb.gather_full(v, sparse_plan)
                elif isinstance(v, _emb.SparseRowGrad):
                    # debug fetch: the dense logical mean gradient
                    v = _emb.densify(v, sparse_plan)
            fetches.append(v)

        def _out_val(n):
            v = env[n]
            if sparse_plan is not None:
                v = _emb.unwrap_state(n, v, sparse_plan)
            if shard_plan is not None:
                v = _su.unwrap_out(n, v, shard_plan)
            return v

        new_states = {n: _out_val(n) for n in state_out if n in env}
        return fetches, new_states

    return fn


def compile_block(program, block, feed_specs, fetch_names, state_specs,
                  donate=None):
    """feed_specs/state_specs: name -> concrete arrays or ShapeDtypeStructs
    (only shapes/dtypes are read). Returns a LoweredFunction."""
    import jax

    if getattr(program, "_pipeline_cfg", None):
        from ..parallel.pipeline import compile_pipeline
        from ..parallel.sharded_update import _record_fallback

        # structured decline, not silence: the pipeline engine owns the
        # program partition, so the unified planner (sparse/TP/ZeRO-1)
        # never runs — perf_analysis --sharded-diff surfaces this entry
        # (one per program; recompiles must not duplicate it)
        trail = getattr(program, "_sharded_update_fallback", None) or []
        if not any(e.get("kind") == "pipeline_bypassed" for e in trail):
            _record_fallback(
                program, "pipeline schedule owns the program "
                "partition; plan_parallel (sparse/TP/ZeRO-1 axis "
                "assignment) is bypassed for _pipeline_cfg programs",
                kind="pipeline_bypassed")
        return compile_pipeline(program, block, feed_specs, fetch_names,
                                state_specs)

    feed_names = list(feed_specs)
    state_in, state_out = analyze_block(block, feed_names, fetch_names)
    missing = [n for n in state_in if n not in state_specs]
    if missing:
        raise RuntimeError(
            "variables %s are read by the program but absent from the scope "
            "— run the startup program (or feed them)" % (missing,))

    from ..parallel import env as penv

    mesh = getattr(program, "_mesh", None)
    if getattr(program, "_data_parallel", False) and mesh is None:
        # FLAGS_tpu_dcn_replicas / PADDLE_NUM_PODS > 1 factors the dp
        # world into a hybrid (dcn, ici) mesh; otherwise the flat
        # single-axis mesh, byte-for-byte the pre-hybrid lowering
        mesh = penv.create_hybrid_mesh() or \
            _default_mesh(getattr(program, "_dp_axis", "dp"))
        program._mesh = mesh
    # derive the axis roles from the mesh itself, so a hand-built
    # hybrid mesh (tests: program._mesh = Mesh(devs.reshape(2, 2),
    # ("dcn", "ici"))) lowers hierarchically without extra marking
    hier = penv.mesh_hierarchy(mesh)
    if hier is not None:
        program._dp_axis = hier[1]   # shard axis = intra-pod ici
        program._dcn_axis = hier[0]
    else:
        program._dcn_axis = None
    dp_axis = getattr(program, "_dp_axis", "dp")

    # ONE planner owns axis assignment (parallel/planner.py): sparse
    # tables → replica rows, tensor parallel → the model axis (via the
    # logical-axis rules), ZeRO-1 flat buffers → the replica axis with
    # TP-local shapes. Planned together so the engines compose instead
    # of colliding, and so the structured-decline trail
    # (program._sharded_update_fallback) covers all three.
    sparse_plan = tp_plan = shard_plan = None
    if mesh is not None and getattr(program, "_data_parallel", False) \
            and getattr(program, "_auto_parallel", None) is None \
            and not getattr(program, "_pipeline_cfg", None):
        from ..parallel import planner as _planner

        pplan = _planner.plan_parallel(program, block, mesh, dp_axis,
                                       feed_names=feed_names,
                                       fetch_names=fetch_names)
        sparse_plan = pplan.sparse_plan
        tp_plan = pplan.tp_plan
        shard_plan = pplan.shard_plan
    program._sparse_plan = sparse_plan
    program._tp_plan = tp_plan
    program._model_axis = tp_plan.model_axis if tp_plan is not None \
        else None
    program._shard_plan = shard_plan

    state_out_set = set(state_out)
    state_mut = [n for n in state_in if n in state_out_set]
    state_ro = [n for n in state_in if n not in state_out_set]
    if sparse_plan is not None:
        # every row-sharded var must flow through the step as scope
        # state (tables of a forward-only program ride state_ro)
        sparse_plan = sparse_plan.prune(state_mut, state_ro)
        program._sparse_plan = sparse_plan

    fn = build_block_fn(program, block, feed_names, fetch_names,
                        state_in, state_out, shard_plan=shard_plan,
                        sparse_plan=sparse_plan, tp_plan=tp_plan)

    if shard_plan is not None:
        # a would-be-sharded state var must flow in AND out of the step;
        # anything else degrades to the replicated layout
        for n in list(shard_plan.sharded_state):
            if n not in state_mut:
                del shard_plan.sharded_state[n]

    if donate is None:  # None = follow the global flag
        from ..utils.flags import get_flag

        donate = bool(get_flag("FLAGS_tpu_donate_buffers", True))
        if donate:
            # persistent compile cache on the CPU backend: deserialized
            # aliased executables are unsafe (state outputs corrupt
            # intermittently — see compile_cache.donation_safe) — drop
            # donation rather than risk silent state corruption
            from . import compile_cache as _ccache

            donate = _ccache.donation_safe()
    from ..utils.flags import get_flag as _gf

    # feed-buffer donation: the executor device_puts a FRESH buffer per
    # step (or consumes a single-use prefetched one), so XLA may reuse
    # feed HBM for scratch/outputs instead of holding both live.
    # Programs whose feeds are ALWAYS caller-owned device arrays
    # (dygraph-to-static subgraphs, jit.load) set _feed_donate=False:
    # donation would buy nothing there (the caller's buffer stays live)
    # while the executor's defensive copy would cost one device copy
    # per feed per step
    feed_donate = donate and \
        bool(_gf("FLAGS_tpu_donate_feed_buffers", True)) and \
        getattr(program, "_feed_donate", True)

    ap_cfg = getattr(program, "_auto_parallel", None)
    if ap_cfg is not None:
        host, dynamic = _block_host_op_kinds(block)
        if host or dynamic:
            import warnings

            warnings.warn(
                "auto-parallel declined: the program contains host/"
                "dynamic-shape ops that cannot run under a GSPMD-"
                "partitioned jit; running single-device instead.")
        else:
            from ..parallel import auto_parallel as ap

            persistable = set()
            for n in state_in:
                v = block._find_var_recursive(n)
                if v is not None and getattr(v, "persistable", False):
                    persistable.add(n)
            # the unified planner owns axis assignment for the GSPMD
            # search too: candidate specs shard each param at the dim
            # the axis rules assign, not a blanket "last axis"
            from ..parallel import planner as _planner

            tp_dims = _planner.param_tp_dims(
                program, block, feed_names=feed_names,
                fetch_names=fetch_names)
            plan = ap.search_plan(fn, feed_specs, state_mut, state_ro,
                                  state_specs, persistable,
                                  configs=ap_cfg, state_out=state_out,
                                  donate=donate, tp_dims=tp_dims)
            program._auto_plan = plan
            jitted = ap.compile_with_plan(fn, plan, feed_names,
                                          state_mut, state_ro, state_out,
                                          donate=donate)
            return LoweredFunction(jitted, feed_names, state_in,
                                   state_out, state_mut, state_ro,
                                   fetch_names, mesh=plan.mesh,
                                   dp_axis="dp", auto_plan=plan)

    if mesh is not None and getattr(program, "_data_parallel", False):
        jitted = _compile_dp(fn, mesh, dp_axis, program, block,
                             feed_names, fetch_names, state_mut, state_ro,
                             donate, feed_donate, shard_plan=shard_plan,
                             tp_plan=tp_plan, state_out=state_out)
    else:
        host, dynamic = _block_host_op_kinds(block)
        if dynamic:
            # NMS-style host ops produce value-dependent output shapes —
            # impossible under XLA (the trace-time shape probe would lie
            # at runtime). The whole block runs unjitted, matching the
            # reference's CPU placement of these kernels.
            jitted = fn
            feed_donate = False
        else:
            # donation is unsafe when an eager retry may rerun with the
            # same buffers after a failed jitted call
            feed_donate = feed_donate and not host
            jitted = jax.jit(
                fn, donate_argnums=_donate_argnums(
                    donate and not host, feed_donate))
            if host:
                # no_jit ops lower to pure_callback under jit; backends
                # without host-callback support (axon PJRT) get the
                # unjitted fallback — same semantics, op-by-op dispatch
                jitted = _jit_with_eager_fallback(jitted, fn)

    return LoweredFunction(jitted, feed_names, state_in, state_out,
                           state_mut, state_ro, fetch_names, mesh=mesh,
                           dp_axis=dp_axis, feed_donate=feed_donate,
                           sharded_state=(dict(shard_plan.sharded_state)
                                          if shard_plan is not None
                                          else None),
                           sparse_tables=(dict(sparse_plan.state_vars)
                                          if sparse_plan is not None
                                          else None))


def _block_host_op_kinds(block):
    """(has_host_ops, has_dynamic_shape_ops) over the block incl.
    sub-blocks."""
    prog = block.program
    host = dynamic = False

    def scan(blk):
        nonlocal host, dynamic
        for op in blk.ops:
            if ops_lib.has_op(op.type):
                od = ops_lib.get_op(op.type)
                host = host or od.no_jit
                dynamic = dynamic or od.dynamic_shape
            for bi in _sub_block_idxs(op):
                scan(prog.block(bi))

    scan(block)
    return host, dynamic


def _jit_with_eager_fallback(jitted, fn):
    state = {"eager": False}

    def call(*args, **kwargs):
        if state["eager"]:
            return fn(*args, **kwargs)
        try:
            return jitted(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 - backend capability probe
            msg = str(e)
            cb = ("callback" in msg or "SendToHost" in msg
                  or "RecvFromHost" in msg)
            unsupported = ("UNIMPLEMENTED" in msg
                           or "not supported" in msg
                           or "does not support" in msg)
            if cb and unsupported:
                # a silent perf cliff otherwise: every later run of this
                # block goes op-by-op eager (axon PJRT lacks host
                # callbacks) — say so once, loudly (VERDICT r3 weak #5)
                import logging

                logging.getLogger("paddle_tpu.lowering").warning(
                    "backend rejected host-callback lowering (%s); "
                    "falling back to UNJITTED op-by-op execution for "
                    "this block from now on — expect a large slowdown. "
                    "Remove host ops (Print/py_func/no_jit ops) from "
                    "the hot path to restore jit.", msg[:200])
                state["eager"] = True
                return fn(*args, **kwargs)
            raise

    return call


# Donated feed buffers that cannot alias an output are simply freed
# after use by XLA — expected, not a bug — but jax warns "Some donated
# buffers were not usable" for them. Filter at MODULE IMPORT, exactly
# once per process: installing lazily at first compile put the filter
# inside whatever warnings.catch_warnings scope happened to be active
# (pytest wraps every test in one), where it silently evaporated. The
# filter also mutes that warning for state donation; the repo does not
# rely on it to catch aliasing regressions — `Executor.donation_report`
# and tests/test_donation.py assert the aliased byte count directly.
import warnings as _warnings

_warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


def _donate_argnums(state_donate, feed_donate):
    """jit donate_argnums for (feeds, states_mut, states_ro, seed)."""
    if feed_donate and state_donate:
        return (0, 1)
    if state_donate:
        return (1,)
    return ()


def _default_mesh(dp_axis):
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices())
    return Mesh(devs, (dp_axis,))


def data_partition_spec(mesh, dp_axis="dp"):
    """PartitionSpec of a data (batch-sharded) tensor on `mesh`: dim 0
    over the whole dp world — both axes of a hybrid (dcn, ici) mesh,
    the single axis otherwise. The one spec feeds/prefetched batches
    and non-persistable fetches share."""
    from jax.sharding import PartitionSpec as P

    from ..parallel import env as penv

    hier = penv.mesh_hierarchy(mesh)
    if hier is not None:
        return P((hier[0], hier[1]))
    return P(dp_axis)


# -- per-collective byte accounting (offline ICI evidence) -------------------

_COLLECTIVE_OPS = ("all_reduce", "reduce_scatter", "all_gather",
                   "all_to_all", "collective_permute")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8": 1,
                "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2,
                "ui16": 2, "i8": 1, "ui8": 1, "i1": 1}


def _tensor_bytes(type_str):
    """bytes of one `tensor<AxBx...xDT>` type string (0 if unparsable)."""
    inner = type_str.strip()
    parts = inner.split("x")
    dt = parts[-1]
    size = _DTYPE_BYTES.get(dt)
    if size is None:
        return 0
    n = 1
    for d in parts[:-1]:
        try:
            n *= int(d)
        except ValueError:
            return 0
    return n * size


def _hlo_collective_hits(stablehlo_text, op_names=_COLLECTIVE_OPS):
    """Ordered `(kind, result_type, open_line, result_line)` hits of
    the collective ops in one StableHLO module text — textual order IS
    program order. Region-bearing ops (all_reduce/reduce_scatter) carry
    their `-> tensor<...>` result type (and the rest of their attrs) on
    the region's CLOSING line, several lines below the op itself.
    Shared by `collective_byte_census` and the divergence checker's
    `analysis.hlo_collective_schedule` so the two never drift."""
    import re

    open_pat = re.compile(
        r"\"?(?:stablehlo|mhlo)\.(%s)\"?" % "|".join(op_names))
    ret_pat = re.compile(r"->\s*(?:tuple<)?tensor<([^>]+)>")
    hits = []
    pending = None
    for line in stablehlo_text.splitlines():
        m = open_pat.search(line)
        r = ret_pat.search(line)
        if m and r:
            hits.append((m.group(1), r.group(1), line, line))
        elif m:
            pending = (m.group(1), line)
        elif pending and r and line.lstrip().startswith("})"):
            hits.append((pending[0], r.group(1), pending[1], line))
            pending = None
    return hits


_HLO_GROUPS_RE = None


def replica_groups_raw(open_line, close_line=""):
    """The raw text of one collective's `replica_groups = dense<...>`
    attribute, or None when absent. Region-bearing ops carry their
    attrs on the region's CLOSING line, so both lines are scanned.
    THE one replica_groups grammar — `parse_replica_groups` and the
    divergence checker's schedule records both read through here, so
    the two can never drift."""
    global _HLO_GROUPS_RE
    import re

    if _HLO_GROUPS_RE is None:
        _HLO_GROUPS_RE = re.compile(
            r"replica_groups\s*=\s*dense<([^>]*)>")
    m = _HLO_GROUPS_RE.search(open_line) or \
        (_HLO_GROUPS_RE.search(close_line) if close_line else None)
    return m.group(1).strip() if m is not None else None


def parse_replica_groups(open_line, close_line=""):
    """`replica_groups` of one StableHLO collective as a tuple of
    member tuples, or None when absent / unparsable."""
    import re

    body = replica_groups_raw(open_line, close_line)
    if not body:
        return None
    try:
        if "[" not in body:  # dense<0> scalar form
            return ((int(body),),)
        groups = []
        for grp in re.findall(r"\[([^\[\]]*)\]", body):
            grp = grp.strip()
            groups.append(tuple(int(t) for t in grp.split(",")) if grp
                          else ())
        return tuple(g for g in groups if g) or None
    except ValueError:
        return None


def classify_replica_groups(groups, ici_size, mp_size=1):
    """"ici" | "dcn" | "mp" lane of one collective's replica_groups on
    a hybrid mesh whose pods are contiguous device blocks (the
    create_hybrid_mesh CPU/emulation layout): a collective whose every
    group stays inside one pod rides the fast intra-pod ICI; any group
    spanning two pods crosses the slow DCN link. With a model axis
    (`mp_size` > 1, the (dcn, replica, model) factorization where
    model is INNERMOST — flat device d has model coord d % mp), a pod
    is `ici_size * mp_size` devices, and a group confined to one
    aligned mp-block (all members share d // mp — same pod, same
    replica) is a tensor-parallel exchange: lane "mp". None when the
    groups are unknown (caller treats the collective as ici — the
    flat-mesh reading)."""
    mp = max(int(mp_size or 1), 1)
    if not groups or ((not ici_size or ici_size <= 1) and mp <= 1):
        return None
    pod = max(int(ici_size or 1), 1) * mp
    for g in groups:
        pods = {d // pod for d in g}
        if len(pods) > 1:
            return "dcn"
    if mp > 1 and any(len(g) > 1 for g in groups) and \
            all(len({d // mp for d in g}) == 1 for g in groups):
        return "mp"
    return "ici"


def _ring_wire_bytes(op, b, n):
    """Ring-algorithm wire bytes of one collective over `n`
    participants: all_reduce 2(N-1)/N of the full tensor,
    reduce_scatter (N-1)x its 1/N result, all_gather (N-1)/N of its
    full result; data-movement ops move their payload once."""
    n = max(int(n), 1)
    if op == "all_reduce":
        return int(2 * (n - 1) / n * b)
    if op == "reduce_scatter":
        return (n - 1) * b
    if op == "all_gather":
        return int((n - 1) / n * b)
    return b


def collective_byte_census(stablehlo_text, ndev=1, ici_size=None,
                           mp_size=None):
    """Per-collective accounting from a lowered StableHLO module:
    {op: {count, tensor_bytes, ici_bytes}} + totals. `tensor_bytes`
    sums the RESULT tensor sizes; `ici_bytes` models ring-algorithm
    wire bytes over each collective's replica_groups participants
    (falling back to the `ndev`-device ring when groups are absent) —
    the quantity the sharded weight update halves on the grad+param
    exchange.

    `ici_size` (hybrid multi-pod mesh): additionally split the census
    into `lanes` — "ici" (intra-pod) vs "dcn" (cross-pod, the slow
    link that bounds grad-sync time at multi-pod scale) — with a
    per-collective byte list per lane, so the hierarchical lowering's
    claim (cross-pod bytes = flat-allreduce bytes / ici_size per
    bucket) is checkable from the census alone.

    `mp_size` (tensor parallelism): a third lane, "mp", for
    model-axis collectives — groups confined to one aligned mp-block
    — reported beside ici/dcn as `mp_bytes_total`, so the TP
    contract (grad-sync bytes confined to the (dcn, replica) axes,
    per-chip param bytes ∝ 1/mp) is checkable from the census too."""
    ndev = max(int(ndev), 1)
    mp = max(int(mp_size or 1), 1)
    out = {op: {"count": 0, "tensor_bytes": 0, "ici_bytes": 0}
           for op in _COLLECTIVE_OPS}
    lane_names = ("ici", "dcn", "mp") if mp > 1 else ("ici", "dcn")
    lanes = {ln: {"count": 0, "tensor_bytes": 0, "wire_bytes": 0,
                  "per_collective": []}
             for ln in lane_names}
    for op, ttype, open_line, close_line in \
            _hlo_collective_hits(stablehlo_text):
        b = _tensor_bytes(ttype)
        groups = parse_replica_groups(open_line, close_line)
        n = max((len(g) for g in groups), default=ndev) if groups \
            else ndev
        rec = out[op]
        rec["count"] += 1
        rec["tensor_bytes"] += b
        rec["ici_bytes"] += _ring_wire_bytes(op, b, n)
        if ici_size or mp > 1:
            lane = classify_replica_groups(groups, ici_size, mp) \
                or "ici"
            lrec = lanes[lane]
            lrec["count"] += 1
            lrec["tensor_bytes"] += b
            lrec["wire_bytes"] += _ring_wire_bytes(op, b, n)
            lrec["per_collective"].append(
                {"kind": op, "tensor_bytes": b, "participants": n})
    out = {k: v for k, v in out.items() if v["count"]}
    out["total_ici_bytes"] = sum(v["ici_bytes"] for v in out.values())
    out["total_tensor_bytes"] = sum(
        v["tensor_bytes"] for v in out.values() if isinstance(v, dict))
    out["ndev"] = ndev
    if ici_size or mp > 1:
        out["lanes"] = lanes
        out["ici_size"] = int(ici_size or 1)
        out["dcn_size"] = ndev // (int(ici_size or 1) * mp)
        out["dcn_bytes_total"] = lanes["dcn"]["wire_bytes"]
        if mp > 1:
            out["mp_size"] = mp
            out["mp_bytes_total"] = lanes["mp"]["wire_bytes"]
    return out


# -- collective/compute overlap audit (offline scheduling evidence) ---------

# opcodes that are pure data movement / bookkeeping: never "backward
# compute" even when they carry vjp metadata
_NONCOMPUTE_OPCODES = frozenset({
    "parameter", "constant", "iota", "tuple", "get-tuple-element",
    "bitcast", "copy", "copy-start", "copy-done", "reshape", "transpose",
    "broadcast", "slice", "dynamic-slice", "dynamic-update-slice",
    "concatenate", "pad", "convert", "partition-id", "replica-id",
    "after-all", "opt-barrier", "all-reduce", "all-reduce-start",
    "all-reduce-done", "reduce-scatter", "reduce-scatter-start",
    "reduce-scatter-done", "all-gather", "all-gather-start",
    "all-gather-done", "all-to-all", "collective-permute",
    "collective-permute-start", "collective-permute-done",
})

_AUDIT_COLLECTIVES = ("reduce-scatter", "all-reduce", "all-gather")

_HLO_SHAPE_RE = None

# optimized-HLO dtype spellings (s32/u32/pred — NOT the StableHLO
# i32/ui32/i1 of _DTYPE_BYTES, which parses lowered-but-unoptimized
# module text)
_HLO_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2,
                    "f8e4m3fn": 1, "f8e5m2": 1,
                    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2,
                    "u16": 2, "s8": 1, "u8": 1, "pred": 1}


def _hlo_result_bytes(result_type):
    """bytes of an HLO instruction's result-type text — SUMS every
    `dt[d1,d2,...]` shape so tuple results (async `-start` ops,
    combiner-merged multi-operand collectives) count whole, not just
    their first element (0 if unparsable)."""
    global _HLO_SHAPE_RE
    import re

    if _HLO_SHAPE_RE is None:
        _HLO_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
    total = 0
    for m in _HLO_SHAPE_RE.finditer(result_type):
        size = _HLO_DTYPE_BYTES.get(m.group(1))
        if size is None:
            continue
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


import re as _re

#: the optimized-HLO instruction grammar, shared with
#: observability/attribution.py's activation-provenance walker so the
#: two parsers can never drift on the dump format
_HLO_INSTR_RE = _re.compile(r"^\s+(?:ROOT\s+)?%([^\s=]+)\s*=\s*(.*)$")
_HLO_OPCODE_RE = _re.compile(r"([a-z][a-z0-9\-]*)\(")
_HLO_OPNAME_RE = _re.compile(r'op_name="([^"]*)"')


def _parse_hlo_module(optimized_hlo):
    """One pass over an optimized HLO dump. Returns (entry, regions):
    `entry` is the ENTRY computation as an ordered list of (name,
    opcode, operand_names, metadata_op_name, result_bytes) — with
    `is_scheduled=true` (every compiled module) the textual order IS
    the schedule; `regions` lists collectives living in NON-entry
    computations (lax.cond / while bodies — gradient merge traces its
    bucketed merged-grad scatters inside the HLO conditional's branch
    computation), fenced by construction: a conditional executes as
    one unit in the entry schedule, so nothing inside it can overlap
    entry backward compute — but the audit must still SEE them rather
    than report 'no collectives' for the gm-sharded path."""
    import re

    name_re = _HLO_INSTR_RE
    opcode_re = _HLO_OPCODE_RE
    opname_re = _HLO_OPNAME_RE
    entry, regions = [], []
    comp = None  # None = between computations; "" = ENTRY
    for line in optimized_hlo.splitlines():
        if line.startswith("ENTRY "):
            comp = ""
            continue
        if line.startswith("%"):  # non-entry computation header
            comp = line.split(" ", 1)[0].lstrip("%")
            continue
        if line.startswith("}"):
            comp = None
            continue
        if comp is None:
            continue
        m = name_re.match(line)
        if m is None:
            continue
        name, rhs = m.group(1), m.group(2)
        om = opcode_re.search(rhs)
        if om is None:
            continue
        opcode = om.group(1)
        # the result type is everything before the opcode name; operand
        # references appear after its open paren (computation refs like
        # to_apply=%region also match but never resolve to entry names)
        nbytes = _hlo_result_bytes(rhs[:om.start()])
        if comp == "":
            operands = re.findall(r"%([\w.\-]+)", rhs[om.end():])
            nm = opname_re.search(rhs)
            entry.append((name, opcode, operands,
                          nm.group(1) if nm else "", nbytes))
        else:
            kind = opcode[:-6] if opcode.endswith("-start") else opcode
            if kind in _AUDIT_COLLECTIVES:
                regions.append({"kind": kind, "name": name,
                                "computation": comp, "bytes": nbytes})
    return entry, regions


def _is_backward_opname(op_name):
    """vjp-generated ops: jax scopes the transpose of the forward trace
    as ".../transpose(jvp(f))/..." (sub-jits) or a bare ".../transpose"
    path component (inline primitives like the dot_general grads)."""
    if "transpose(" in op_name:
        return True
    return any(part == "transpose" for part in op_name.split("/"))


def collective_overlap_audit(optimized_hlo):
    """Scheduling audit over an optimized (scheduled) HLO dump: can the
    grad collectives overlap backward compute, or are they fenced at
    the end of the backward pass?

    For every reduce-scatter / all-reduce / all-gather in the entry
    schedule, `ready` is the dataflow-ready position (max schedule
    position of its operands) — the earliest point the transfer could
    start — and `backward_after` counts backward-compute instructions
    (vjp-metadata ops that are not pure data movement) scheduled after
    it: the compute a latency-hiding scheduler can run DURING the
    transfer. `combined` models XLA's collective combiner merging all
    same-kind collectives into one (what the per-variable lowering
    degenerates to on real ICI without
    --xla_*_combine_threshold_bytes): its ready position is the max
    over members, so the single-buffer exchange shows backward_after=0
    — nothing left to hide behind. The bucketed lowering
    (FLAGS_tpu_comm_bucket_mb > 0) is the point of this audit: early
    buckets' reduce-scatters must show backward_after > 0."""
    instrs, region_collectives = _parse_hlo_module(optimized_hlo)
    pos = {name: i for i, (name, _, _, _, _) in enumerate(instrs)}
    backward = [i for i, (_, opc, _, op_name, _) in enumerate(instrs)
                if op_name and _is_backward_opname(op_name)
                and opc not in _NONCOMPUTE_OPCODES]
    final_backward = max(backward) if backward else -1
    collectives = []
    for i, (name, opc, operands, _, nbytes) in enumerate(instrs):
        kind = opc[:-6] if opc.endswith("-start") else opc
        if kind not in _AUDIT_COLLECTIVES:
            continue
        ready = max([pos[o] for o in operands if o in pos] or [-1])
        after = sum(1 for b in backward if b > ready)
        collectives.append({
            "kind": kind, "name": name, "pos": i, "ready": ready,
            "backward_after": after, "bytes": nbytes,
            "starts_before_final_backward": ready < final_backward,
        })
    combined = {}
    for kind in _AUDIT_COLLECTIVES:
        members = [c for c in collectives if c["kind"] == kind]
        if not members:
            continue
        ready = max(c["ready"] for c in members)
        combined[kind] = {
            "count": len(members),
            "ready": ready,
            "backward_after": sum(1 for b in backward if b > ready),
            "bytes": sum(c["bytes"] for c in members),
        }
    return {
        "is_scheduled": "is_scheduled=true" in
                        optimized_hlo[:optimized_hlo.find("\n")],
        "n_instructions": len(instrs),
        "n_backward_compute": len(backward),
        "final_backward_pos": final_backward,
        "collectives": collectives,
        "overlappable_reduce_scatters": sum(
            1 for c in collectives
            if c["kind"] == "reduce-scatter" and c["backward_after"] > 0),
        "combined": combined,
        # collectives inside cond/while region computations (gradient
        # merge): fenced by construction — a conditional executes as
        # one unit, nothing inside can overlap the entry schedule
        "region_collectives": region_collectives,
    }


def _compile_dp(fn, mesh, dp_axis, program, block, feed_names, fetch_names,
                state_mut, state_ro, donate, feed_donate=False,
                shard_plan=None, tp_plan=None, state_out=None):
    """Data-parallel lowering: shard_map over the mesh; feeds sharded on
    axis 0, state replicated. Collective ops inside see the live axis and
    emit psum over ICI (reference flow: transpiler/collective.py:178-268 +
    c_allreduce kernels -> here SURVEY.md §3C TPU mapping). With a
    shard_plan, optimizer-state vars get P(dp_axis) in/out specs — their
    scope arrays are flat buffers sharded over the mesh, so per-replica
    optimizer HBM is ~1/N across steps (ZeRO-1).

    With a tp_plan, state splits into FOUR layouts: replicated P();
    ZeRO flat buffers P(dp); ZeRO flat buffers of model-sharded vars
    P((model, dp)) — the model-major concat of per-member local flats;
    and model-sharded params P(model @ their tp_dim) — the scope keeps
    LOGICAL shapes, shard_map hands each device its local block
    (save-logical / restore-sharded falls out of the specs, no
    checkpoint special-casing)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel import env as penv

    ndev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    axes = {a: mesh.shape[a] for a in mesh.axis_names}
    # vocab-sharded embedding tables + per-row moments share the
    # dp-axis in/out spec with the ZeRO flat buffers: P(dp_axis) on a
    # (padded_rows, dim) buffer shards dim 0 over the (intra-pod)
    # axis and replicates across dcn pods — the same layout rule
    sparse_plan = getattr(program, "_sparse_plan", None)
    row_sharded = frozenset(sparse_plan.state_vars) \
        if sparse_plan is not None else frozenset()
    sharded_names = (frozenset(shard_plan.sharded_state)
                     if shard_plan is not None else frozenset()) \
        | row_sharded
    # hybrid (dcn, ici) mesh: data (batch) shards over BOTH data axes —
    # row-major, so device (pod p, chip j) holds the same batch slice
    # as flat device p*ici+j — while sharded opt-state stays P(ici)
    # only (each pod holds a full copy of the 1/ici shards). The model
    # axis NEVER carries data: its mp members duplicate the batch slice
    # and hold distinct weight shards instead.
    hier = penv.mesh_hierarchy(mesh)
    data_axes = (hier[0], hier[1]) if hier is not None else dp_axis
    mp_axis = tp_plan.model_axis if tp_plan is not None else None
    # ZeRO'd vars that are ALSO model-sharded ride P((model, dp)) flat
    # buffers; model-sharded vars NOT in ZeRO state (live params, or
    # moments when the ZeRO planner declined) keep logical shapes in
    # scope with P(model @ tp_dim)
    zero_tp = frozenset(
        n for n, info in shard_plan.sharded_state.items()
        if info.tp_dim is not None) if shard_plan is not None \
        else frozenset()
    tp_only = frozenset(tp_plan.var_dims) - sharded_names \
        if tp_plan is not None else frozenset()

    def tp_spec(n):
        return tp_plan.spec_for(n)

    def wrapped(feeds, states_mut, states_ro, seed):
        with penv.collective_scope(axes):
            fetches, new_states = fn(feeds, states_mut, states_ro, seed)
        # split state outs by layout: shard_map needs distinct out
        # specs for replicated vs dp-sharded vs model-sharded state
        rep, sh, sh_ztp, sh_tp = {}, {}, {}, {}
        for n, v in new_states.items():
            if n in zero_tp:
                sh_ztp[n] = v
            elif n in sharded_names:
                sh[n] = v
            elif n in tp_only:
                sh_tp[n] = v
            else:
                rep[n] = v
        return fetches, rep, sh, sh_ztp, sh_tp

    feed_specs = {n: P(data_axes) for n in feed_names}

    def state_spec(n):
        if n in zero_tp:
            return P((mp_axis, dp_axis))
        if n in sharded_names:
            return P(dp_axis)
        if n in tp_only:
            return tp_spec(n)
        return P()

    state_specs_mut = {n: state_spec(n) for n in state_mut}
    # forward-only programs hold their sparse tables (and model-sharded
    # params) as read-only state — still sharded
    state_specs_ro = {n: state_spec(n) if n in tp_only
                      else (P(dp_axis) if n in row_sharded else P())
                      for n in state_ro}
    # out specs for the model-sharded group need the per-name tp_dim, so
    # the names must be static: state_out is the traced fn's exact
    # new_states key set
    out_names = state_out if state_out is not None else state_mut
    tp_out_specs = {n: tp_spec(n) for n in out_names if n in tp_only}

    def out_spec_for_fetch(n):
        if sparse_plan is not None and (
                n in row_sharded or n in sparse_plan.grad_of):
            # gathered table / densified SelectedRows grad: replicated
            return P()
        v = block._find_var_recursive(n)
        if v is not None and v.persistable:
            return P()
        return P(data_axes)

    # state_out names are discovered inside fn; replicated except the
    # plan's sharded optimizer state
    fetch_specs = [out_spec_for_fetch(n) for n in fetch_names]

    from ..parallel.env import shard_map_compat

    smapped = shard_map_compat(
        wrapped, mesh=mesh,
        in_specs=(feed_specs, state_specs_mut, state_specs_ro, P()),
        out_specs=(fetch_specs, P(), P(dp_axis),
                   P((mp_axis, dp_axis)) if mp_axis is not None
                   else P(dp_axis), tp_out_specs),
        check_vma=False)

    def merged(feeds, states_mut, states_ro, seed):
        fetches, rep, sh, sh_ztp, sh_tp = smapped(
            feeds, states_mut, states_ro, seed)
        rep.update(sh)
        rep.update(sh_ztp)
        rep.update(sh_tp)
        return fetches, rep

    return jax.jit(merged,
                   donate_argnums=_donate_argnums(donate, feed_donate))
