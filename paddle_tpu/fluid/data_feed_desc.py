"""DataFeedDesc — training-data format descriptor (reference:
`python/paddle/fluid/data_feed_desc.py:21` wrapping the
`framework/data_feed.proto` text message). TPU-native: a small text
parser/printer with the same accessor surface; `fluid.dataset` slot
configuration is the consumer."""
from __future__ import annotations


class _Slot:
    __slots__ = ("name", "type", "is_dense", "is_used")

    def __init__(self, name="", type="uint64", is_dense=False,
                 is_used=False):
        self.name = name
        self.type = type
        self.is_dense = is_dense
        self.is_used = is_used


class DataFeedDesc:
    """Parse a data_feed prototxt (name / batch_size /
    multi_slot_desc{slots{...}}), expose the reference's mutators, and
    print the message back out via `desc()`."""

    def __init__(self, proto_file):
        self.name = "MultiSlotDataFeed"
        self.batch_size = 1
        self._slots = []
        self._slot_by_name = {}
        with open(proto_file) as f:
            self._parse(f.read())

    def _parse(self, text):
        cur = None
        for raw in text.splitlines():
            ln = raw.strip()
            if not ln or ln.startswith("#"):
                continue
            if ln.startswith("slots") and ln.endswith("{"):
                cur = _Slot()
                continue
            if ln == "}":
                if cur is not None and cur.name:
                    self._slots.append(cur)
                    self._slot_by_name[cur.name] = cur
                cur = None
                continue
            if ln.endswith("{"):
                continue  # multi_slot_desc {
            if ":" not in ln:
                continue
            k, v = ln.split(":", 1)
            k, v = k.strip(), v.strip().strip('"')
            if cur is not None:
                if k == "name":
                    cur.name = v
                elif k == "type":
                    cur.type = v
                elif k == "is_dense":
                    cur.is_dense = v == "true"
                elif k == "is_used":
                    cur.is_used = v == "true"
            elif k == "name":
                self.name = v
            elif k == "batch_size":
                self.batch_size = int(v)

    # -- reference mutators (data_feed_desc.py:75-160) -----------------
    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_dense_slots(self, dense_slots_name):
        for n in dense_slots_name:
            if n not in self._slot_by_name:
                raise ValueError("slot %r not found" % n)
            self._slot_by_name[n].is_dense = True

    def set_use_slots(self, use_slots_name):
        for n in use_slots_name:
            if n not in self._slot_by_name:
                raise ValueError("slot %r not found" % n)
            self._slot_by_name[n].is_used = True

    def slot_names(self):
        return [s.name for s in self._slots]

    def desc(self):
        """The message back in protobuf text format."""
        lines = ['name: "%s"' % self.name,
                 "batch_size: %d" % self.batch_size,
                 "multi_slot_desc {"]
        for s in self._slots:
            lines += ["  slots {",
                      '    name: "%s"' % s.name,
                      '    type: "%s"' % s.type,
                      "    is_dense: %s" % str(s.is_dense).lower(),
                      "    is_used: %s" % str(s.is_used).lower(),
                      "  }"]
        lines.append("}")
        return "\n".join(lines) + "\n"
