"""Training-graph fusion rewrites behind BuildStrategy knobs
(reference: `framework/ir/fuse_elewise_add_act_pass.cc` and
`ir/fuse_bn_act_pass.cc`). On TPU, XLA fuses these elementwise chains
at compile time anyway — the rewrites shrink the traced program (fewer
ops to trace + lower), and make the strategy knobs real rather than
decorative. Both run BEFORE lowering, so autodiff is unaffected:
jax.vjp differentiates the fused forward exactly like the composition.

keep_names: vars observed externally (this run's fetch targets) — a
fused-away intermediate that is fetched must stay producible, so such
pairs are skipped. The rewrite is once-per-program (idempotent marker);
a LATER run fetching an already-fused-away intermediate cannot be
served — fetch-sensitive callers should fuse after deciding fetches,
which Executor.run's wiring does for the first run.
"""
from __future__ import annotations

from .framework import Operator

_EW_ACTS = ("relu", "sigmoid", "tanh")


def _fuse_pairs(program, marker, match_producer, match_consumer,
                build_replacement, keep_names=()):
    """Shared producer->sole-consumer pattern rewrite: for each op
    where match_producer(op) and whose single output consumer satisfies
    match_consumer, replace the producer with build_replacement(...)
    and drop the consumer. Guards: the intermediate must not be
    persistable or in keep_names."""
    if getattr(program, marker, False):
        return 0
    from . import lowering

    block = program.global_block()
    ops = list(block.ops)
    keep = set(keep_names)
    # consumer map via lowering's recursive read analysis: a var read
    # only inside a while/cond/scan sub-block is still a consumer
    # (control-flow ops don't declare enclosing-env reads as op inputs
    # — ADVICE r4: input_arg_names alone left sub-block-read Y's
    # silently unproduced after fuse_bn_act renamed them)
    consumers = {}
    for i, op in enumerate(ops):
        reads, _ = lowering._op_reads_writes(op)
        for n in set(reads):
            consumers.setdefault(n, []).append(i)

    fused = 0
    to_remove = set()
    for i, op in enumerate(ops):
        if i in to_remove or not match_producer(op):
            continue
        out = match_producer(op)  # the intermediate var name
        if out in keep:
            continue
        v = block._find_var_recursive(out)
        if v is not None and getattr(v, "persistable", False):
            continue
        cons = consumers.get(out, [])
        if len(cons) != 1 or cons[0] in to_remove:
            continue
        act = ops[cons[0]]
        if not match_consumer(act):
            continue
        replacement = build_replacement(block, op, act)
        if replacement is None:
            continue
        ops[i] = replacement
        to_remove.add(cons[0])
        fused += 1
    if fused:
        block.ops = [op for k, op in enumerate(ops)
                     if k not in to_remove]
        program._version += 1
    setattr(program, marker, True)
    return fused


def fuse_elewise_add_act(program, keep_names=()) -> int:
    """[elementwise_add -> relu/sigmoid/tanh] pairs whose intermediate
    is otherwise dead become one fused_elemwise_activation op
    (functor_list=[act, "elementwise_add"], the reference's
    outer-first convention). Returns pairs fused."""

    def build(block, op, act):
        x = block._find_var_recursive(op.input_names["X"][0])
        y = block._find_var_recursive(op.input_names["Y"][0])
        inter = block._find_var_recursive(op.output_names["Out"][0])
        act_out = block._find_var_recursive(act.output_names["Out"][0])
        if x is None or y is None or act_out is None:
            return None
        return Operator(
            block, "fused_elemwise_activation",
            inputs={"X": [x], "Y": [y]},
            outputs={"Out": [act_out], "IntermediateOut": [inter]},
            attrs={"functor_list": [act.type, "elementwise_add"],
                   "axis": op.attrs.get("axis", -1)})

    return _fuse_pairs(
        program, "_ew_act_fused",
        lambda op: (op.output_names["Out"][0]
                    if op.type == "elementwise_add" else None),
        lambda act: act.type in _EW_ACTS,
        build, keep_names)


def fuse_bn_act(program, keep_names=()) -> int:
    """[batch_norm -> relu] with a solely-consumed Y folds the
    activation into the batch_norm op (attrs['fused_act']); the BN's
    normalized output is renamed to the relu's output so downstream
    consumers are untouched. Returns pairs fused."""

    def build(block, op, act):
        act_out = block._find_var_recursive(act.output_names["Out"][0])
        if act_out is None:
            return None
        # the BN's original Y name disappears from the program: record
        # it so a LATER run fetching it gets a descriptive error naming
        # the knob instead of lowering's generic "never computed"
        dropped = block.program._fused_away_vars = getattr(
            block.program, "_fused_away_vars", {})
        dropped[op.output_names["Y"][0]] = "fuse_bn_act_ops"
        inputs = {slot: [block._find_var_recursive(n) for n in names]
                  for slot, names in op.input_names.items() if names}
        outputs = {slot: [block._find_var_recursive(n) for n in names]
                   for slot, names in op.output_names.items() if names}
        outputs["Y"] = [act_out]
        attrs = {k: v for k, v in op.attrs.items()
                 if not k.startswith("_")}
        attrs["fused_act"] = "relu"
        return Operator(block, "batch_norm", inputs=inputs,
                        outputs=outputs, attrs=attrs)

    return _fuse_pairs(
        program, "_bn_act_fused",
        lambda op: (op.output_names["Y"][0]
                    if op.type == "batch_norm"
                    and not op.attrs.get("fused_act") else None),
        lambda act: act.type == "relu",
        build, keep_names)
