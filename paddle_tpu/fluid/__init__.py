"""paddle_tpu.fluid — the Fluid programming model, TPU-native.

Reference parity: `python/paddle/fluid/__init__.py`. Static ProgramDesc
graphs + Executor, dygraph imperative mode, layers/optimizer/io APIs — all
lowering to XLA on TPU.
"""
from . import framework
from .framework import (  # noqa: F401
    Program, Variable, Parameter, Operator, program_guard,
    default_main_program, default_startup_program, name_scope,
    device_guard, in_dygraph_mode, cpu_places, cuda_places, tpu_places,
    CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace,
    unique_name_guard, require_version, is_compiled_with_cuda,
    load_op_library, ComplexVariable,
)
from . import unique_name  # noqa: F401
from .parallel_executor import ParallelExecutor  # noqa: F401
from .. import core  # noqa: F401  (fluid.core.CipherUtils etc.)
from ..core.scope import Scope, global_scope, scope_guard  # noqa: F401
from ..core.lod import (  # noqa: F401
    LoDTensor, create_lod_tensor, create_random_int_lodtensor,
)
from .executor import Executor, LazyFetch  # noqa: F401
from .backward import append_backward, gradients  # noqa: F401
from .fuse_optimizer import fuse_optimizer_ops  # noqa: F401
from .compiler import (  # noqa: F401
    CompiledProgram, BuildStrategy, ExecutionStrategy,
)
from . import layers  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from .clip import (  # noqa: F401
    GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm,
)
from .initializer import (  # noqa: F401
    Constant, Uniform, Normal, TruncatedNormal, Xavier, MSRA,
    NumpyArrayInitializer,
)
from . import dygraph  # noqa: F401
from .dygraph.base import enable_dygraph, disable_dygraph  # noqa: F401
from . import io  # noqa: F401
from .io import (  # noqa: F401
    save_persistables, load_persistables, save_params, load_params,
    save_inference_model, load_inference_model,
)
from . import reader  # noqa: F401
from .reader import DataLoader, BatchSampler  # noqa: F401
from . import dataset  # noqa: F401
from .dataset import DatasetFactory, InMemoryDataset, QueueDataset  # noqa: F401
from . import metrics  # noqa: F401
from . import average  # noqa: F401
from . import evaluator  # noqa: F401
from . import debugger  # noqa: F401
from . import communicator  # noqa: F401
from .data_feed_desc import DataFeedDesc  # noqa: F401
from .input import embedding, one_hot  # noqa: F401
from . import contrib  # noqa: F401
from . import install_check  # noqa: F401
from . import incubate  # noqa: F401
from . import profiler  # noqa: F401
from . import checkpoint  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401


def data(name, shape, dtype="float32", lod_level=0):
    """fluid.data — batch dim must be explicit/-1 (reference:
    python/paddle/fluid/data.py)."""
    return layers.tensor.data(name, shape, dtype=dtype,
                              append_batch_size=False)


# flags system (reference: platform/flags.cc surfaced via
# global_value_getter_setter.cc)
from ..utils.flags import get_flags, set_flags  # noqa: F401,E402

# parameter-server transpiler (reference: fluid.DistributeTranspiler)
from . import transpiler  # noqa: F401,E402
from .transpiler import (  # noqa: F401,E402
    DistributeTranspiler, DistributeTranspilerConfig,
    memory_optimize, release_memory,
)

# composite network builders (reference: python/paddle/fluid/nets.py)
from . import nets  # noqa: F401,E402
