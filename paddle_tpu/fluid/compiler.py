"""CompiledProgram (reference: `python/paddle/fluid/compiler.py:87-310`).

`with_data_parallel` marks the program for SPMD lowering over the device
mesh: the reference's per-device graph clones + AllReduceOpHandles
(multi_devices_graph_pass.cc) collapse into one shard_map'd XLA computation
(SURVEY.md §3B TPU mapping).
"""
from __future__ import annotations


class BuildStrategy:
    """Accepted for API compatibility; most knobs are XLA's job now."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_reduce_ops = None
        self.fuse_all_optimizer_ops = None
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.nccl_comm_num = 1
        self.num_trainers = 1
        self.trainer_id = 0
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0
        self.sync_batch_norm = False
        self.enable_sequential_execution = False
        self.remove_unnecessary_lock = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._is_data_parallel = True
        if build_strategy is not None:
            self._build_strategy = build_strategy
        p = self._program
        p._data_parallel = True
        if places is not None and p._mesh is None:
            import numpy as np
            from jax.sharding import Mesh

            devs = np.array([pl.jax_device() for pl in places])
            p._mesh = Mesh(devs, (p._dp_axis,))
        return self

    def with_inference_optimize(self, config):
        return self

    def _unwrap(self):
        return self._program


CompiledProgram.__doc__ = (CompiledProgram.__doc__ or "") + \
    "\nReference: compiler.py:87 (CompiledProgram), :160 (with_data_parallel)"
