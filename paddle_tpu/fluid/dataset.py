"""Dataset API over the C++ native data feed.

Reference: `python/paddle/fluid/dataset.py` (DatasetFactory,
InMemoryDataset, QueueDataset) driving the C++ MultiSlotDataFeed
(`framework/data_feed.cc:639`) and Dataset shuffle (`data_set.h:111`).

TPU-native: parsing/shuffle/batching run in C++ threads
(paddle_tpu.core.native.MultiSlotDataFeed); batches surface as numpy
arrays which the executor device_puts — XLA overlaps the transfer with
compute. Variable-length slots are padded dense + a `<name>.lod` offsets
array (LoD kept as host metadata; see SURVEY.md §7 hard part (a)).
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from ..core import native


class DatasetFactory:
    """Reference: dataset.py DatasetFactory.create_dataset."""

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError("unknown dataset class %r" % datafeed_class)


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread = 1
        self._filelist: List[str] = []
        self._use_vars = []
        self._shuffle_seed = 0
        self._pipe_command = None
        self._queue_capacity = 16

    # -- configuration (reference dataset.py setters) ----------------------
    def set_batch_size(self, batch_size: int):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num: int):
        self._thread = max(1, int(thread_num))

    def set_filelist(self, filelist: Sequence[str]):
        self._filelist = list(filelist)

    def set_use_var(self, var_list):
        """Declares the slot order: one var per slot, dtype float32/int64."""
        self._use_vars = list(var_list)

    def set_pipe_command(self, pipe_command: str):
        """Each input file is piped through this shell command before
        MultiSlot parsing (reference: data_feed.proto pipe_command,
        applied per-file in DataFeed). Applied in _make_feed."""
        self._pipe_command = pipe_command

    def set_queue_num(self, queue_num: int):
        self._queue_capacity = max(2, int(queue_num))

    # -- derived -----------------------------------------------------------
    def _slot_types(self) -> List[str]:
        types = []
        for v in self._use_vars:
            dt = str(getattr(v, "dtype", "float32"))
            types.append("int64" if "int" in dt else "float32")
        return types

    def _effective_filelist(self) -> List[str]:
        """Applies pipe_command (if set) by piping each file through the
        shell command into temp files handed to the native parser. Piped
        files are cached (one run per source file, reused across epochs)
        and unlinked when the dataset is dropped."""
        if not self._pipe_command:
            return self._filelist
        import subprocess
        import tempfile

        key = (self._pipe_command, tuple(self._filelist))
        if getattr(self, "_piped_key", None) == key:
            return self._piped_files
        self._cleanup_piped()
        out_files = []
        for path in self._filelist:
            tmp = tempfile.NamedTemporaryFile(
                mode="wb", suffix=".multislot", delete=False)
            try:
                with open(path, "rb") as fin:
                    subprocess.run(self._pipe_command, shell=True,
                                   stdin=fin, stdout=tmp, check=True)
            except BaseException:
                tmp.close()
                os.unlink(tmp.name)
                for f in out_files:
                    os.unlink(f)
                raise
            tmp.close()
            out_files.append(tmp.name)
        self._piped_files = out_files
        self._piped_key = key
        return out_files

    def _cleanup_piped(self):
        for f in getattr(self, "_piped_files", []):
            try:
                os.unlink(f)
            except OSError:
                pass
        self._piped_files = []
        self._piped_key = None

    def __del__(self):
        try:
            self._cleanup_piped()
        except Exception:
            pass

    def _make_feed(self) -> native.MultiSlotDataFeed:
        if not self._use_vars:
            raise ValueError("set_use_var must be called before use")
        if not self._filelist:
            raise ValueError("set_filelist must be called before use")
        feed = native.MultiSlotDataFeed(self._slot_types(),
                                        self._batch_size,
                                        self._queue_capacity)
        feed.set_filelist(self._effective_filelist())
        return feed

    def _batches_from_feed(self, feed: native.MultiSlotDataFeed,
                           shuffle: bool):
        feed.start(n_threads=self._thread, shuffle=shuffle,
                   seed=self._shuffle_seed)
        for slots in feed:
            yield self._decode_batch(
                [(vals, lod) for vals, lod in slots])
        feed.join()

    def _decode_batch(self, slots):
        """Slot arrays -> feed dict. The output schema is keyed on the
        DECLARED var (lod_level), not per-batch data, so every batch of a
        lod slot carries `<name>.lod` even when lengths align."""
        out = {}
        for v, (vals, lod) in zip(self._use_vars, slots):
            name = v.name
            shape = tuple(getattr(v, "shape", ()) or ())
            lod_level = getattr(v, "lod_level", 0) or 0
            n_examples = len(lod) - 1
            counts = np.diff(lod)
            if lod_level > 0:
                # sequence slot -> pad with 0, expose offsets as .lod.
                # Pad width is bucketed to the next power of two so batch
                # shapes repeat and the executor's shape-keyed compile
                # cache stays warm (SURVEY.md §7 hard part (d)).
                width = int(counts.max()) if counts.size else 0
                if width > 0:
                    width = 1 << (width - 1).bit_length()
                # native scatter: one memcpy per row (ragged.cc) instead
                # of a python loop
                from ..core.native import ragged_pad

                arr = ragged_pad(vals.reshape(-1, 1), counts,
                                 max_len=width)[..., 0]
                out[name + ".lod"] = np.asarray(lod)
            else:
                if counts.size and not (counts == counts[0]).all():
                    raise ValueError(
                        "slot %r has ragged lengths %s but var %s declares "
                        "lod_level=0 — declare lod_level=1 for sequence "
                        "slots" % (name, sorted(set(counts.tolist())), name))
                arr = vals.reshape(n_examples, int(counts[0])
                                   if counts.size else 0)
                if arr.shape[1] == 1 and len(shape) <= 1:
                    arr = arr[:, 0]
            out[name] = arr
        return out

    def _iter_batches(self):
        raise NotImplementedError


class QueueDataset(DatasetBase):
    """Streaming dataset: files are parsed on demand each epoch, no
    global shuffle (reference: dataset.py QueueDataset)."""

    def local_shuffle(self):
        raise RuntimeError("QueueDataset does not support local_shuffle; "
                           "use InMemoryDataset")

    def global_shuffle(self, fleet=None):
        raise RuntimeError("QueueDataset does not support global_shuffle; "
                           "use InMemoryDataset")

    def _iter_batches(self):
        yield from self._batches_from_feed(self._make_feed(), shuffle=False)


class InMemoryDataset(DatasetBase):
    """Loads all examples into memory once; supports local/global shuffle
    (reference: dataset.py InMemoryDataset, data_set.h:111)."""

    def __init__(self):
        super().__init__()
        self._examples: Optional[list] = None
        self._do_shuffle = False

    def load_into_memory(self):
        # materialize per-example records by draining the native feed with
        # batch_size 1 semantics kept at batch level: store raw batches of
        # size 1 example for exact reshuffling
        feed = native.MultiSlotDataFeed(self._slot_types(), 1,
                                        self._queue_capacity)
        feed.set_filelist(self._effective_filelist())
        feed.start(n_threads=self._thread, shuffle=False)
        self._examples = list(feed)
        feed.join()

    def local_shuffle(self):
        self._do_shuffle = True

    def global_shuffle(self, fleet=None, thread_num=12):
        """Cross-trainer record exchange (reference: data_set.h:111
        Dataset::GlobalShuffle over Gloo). Multi-host: every trainer
        allgathers the record set over the host-collective store
        (distributed/host_collectives.py — the Gloo-equivalent tier),
        applies one shared global permutation, and keeps its
        rank-strided slice. Single-host: local shuffle."""
        self._do_shuffle = True
        from ..distributed.host_collectives import group_from_env

        group = group_from_env()
        if group is None:
            return
        if self._examples is None:
            self.load_into_memory()
        try:
            # sharded exchange (reference Dataset::GlobalShuffle routes
            # each record to exactly ONE target): never materialize the
            # whole dataset on any rank. Each rank permutes its local
            # records and deals them round-robin to targets; the store
            # holds only in-flight per-edge blobs (removed on take).
            seed = int(group.broadcast(
                np.asarray([np.random.randint(0, 2**31 - 1)], np.int64),
                root=0)[0])
            rng = np.random.RandomState((seed + 131 * group.rank)
                                        % (2**31 - 1))
            perm = rng.permutation(len(self._examples))
            buckets = [[] for _ in range(group.world)]
            for pos, idx in enumerate(perm):
                buckets[pos % group.world].append(self._examples[idx])
            for dst in range(group.world):
                group.put("shuf/%d/%d" % (group.rank, dst),
                          _encode_examples(buckets[dst]))
            received = []
            for src in range(group.world):
                received.extend(_decode_examples(
                    group.take("shuf/%d/%d" % (src, group.rank))))
            np.random.RandomState((seed * 7 + group.rank)
                                  % (2**31 - 1)).shuffle(received)
            self._examples = received
            # all ranks must finish their takes before rank 0 tears the
            # store down (slow-rank race otherwise)
            group.barrier()
        finally:
            group.shutdown()

    def release_memory(self):
        self._examples = None

    def get_memory_data_size(self, fleet=None):
        return len(self._examples) if getattr(self, "_examples", None) \
            is not None else 0

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size(fleet)

    def set_fleet_send_batch_size(self, fleet_send_batch_size=1024):
        pass

    def _iter_batches(self):
        if getattr(self, "_examples", None) is None:
            # not preloaded: stream like QueueDataset (with shuffle if set)
            yield from self._batches_from_feed(self._make_feed(),
                                               shuffle=self._do_shuffle)
            return
        order = np.arange(len(self._examples))
        if self._do_shuffle:
            rng = np.random.RandomState(self._shuffle_seed)
            rng.shuffle(order)
            self._shuffle_seed += 1
        bs = self._batch_size
        n_slots = len(self._use_vars)
        for start in range(0, len(order), bs):
            sel = order[start:start + bs]
            slots = []
            for s in range(n_slots):
                vals_list = [self._examples[i][s][0] for i in sel]
                counts = np.array([len(v) for v in vals_list])
                lod = np.concatenate([[0], np.cumsum(counts)])
                slots.append((np.concatenate(vals_list), lod))
            yield self._decode_batch(slots)


def _encode_examples(examples) -> "np.ndarray":
    """Serialize [example][slot] = (vals, lod) into one uint8 blob.
    Layout is per-SLOT concatenation (vals concat + per-example value
    counts + lod concat + per-example lod lengths): 4 npz members per
    slot regardless of example count, instead of 2 members per
    (example, slot) — zip-member overhead stays O(slots), not
    O(records)."""
    import io

    n_slots = len(examples[0]) if examples else 0
    arrays = {"__n__": np.asarray([len(examples), n_slots], np.int64)}
    for s_i in range(n_slots):
        vals_list = [np.asarray(ex[s_i][0]) for ex in examples]
        lods_list = [np.asarray(ex[s_i][1]) for ex in examples]
        arrays["v%d" % s_i] = np.concatenate(vals_list) if vals_list \
            else np.zeros((0,), np.float32)
        arrays["vc%d" % s_i] = np.asarray(
            [v.size for v in vals_list], np.int64)
        arrays["l%d" % s_i] = np.concatenate(lods_list) if lods_list \
            else np.zeros((0,), np.int64)
        arrays["lc%d" % s_i] = np.asarray(
            [l.size for l in lods_list], np.int64)
    bio = io.BytesIO()
    np.savez(bio, **arrays)
    return np.frombuffer(bio.getvalue(), dtype=np.uint8)


def _decode_examples(blob: "np.ndarray"):
    import io

    with np.load(io.BytesIO(blob.tobytes())) as z:
        n_examples, n_slots = (int(v) for v in z["__n__"])
        per_slot = []
        for s_i in range(n_slots):
            vals = z["v%d" % s_i]
            vc = np.cumsum(np.concatenate([[0], z["vc%d" % s_i]]))
            lods = z["l%d" % s_i]
            lc = np.cumsum(np.concatenate([[0], z["lc%d" % s_i]]))
            per_slot.append((vals, vc, lods, lc))
        out = []
        for i in range(n_examples):
            ex = []
            for vals, vc, lods, lc in per_slot:
                ex.append((vals[vc[i]:vc[i + 1]],
                           lods[lc[i]:lc[i + 1]]))
            out.append(ex)
    return out
