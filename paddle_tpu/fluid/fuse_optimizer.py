"""Coalesced optimizer updates — the reference's
fuse_optimizer_ops_pass family (`framework/ir/fuse_optimizer_ops_pass/`:
fuse_sgd/momentum/adam over coalesced gradient buffers), re-done as a
program rewrite: N same-configured sgd/momentum/adam ops collapse into
ONE fused_* op whose compute flattens the group into a single vector
(ops/optimizer_ops.py fused_*). Math is exactly preserved — elementwise
updates are concat/split-stable and per-param scalars (adam beta pows)
broadcast into their own segments.

Why it matters on TPU: per-parameter update chains dominated the train
step's StableHLO (ResNet50: ~60% of lines), which is compile-time, not
runtime — XLA horizontal fusion already merges the runtime loops. The
fused form shrinks the program the tunnel-window compile must swallow.

Entry points: `fuse_optimizer_ops(program)` (idempotent), honored by
`BuildStrategy.fuse_all_optimizer_ops` through Executor.run on a
CompiledProgram.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from . import lowering

# op type -> (input slots to coalesce, output slots produced per member)
_FUSABLE: Dict[str, Tuple[Tuple[str, ...], Tuple[str, ...]]] = {
    "sgd": (("Param", "Grad"), ("ParamOut",)),
    "momentum": (("Param", "Grad", "Velocity"),
                 ("ParamOut", "VelocityOut")),
    "adam": (("Param", "Grad", "Moment1", "Moment2", "Beta1Pow",
              "Beta2Pow"),
             ("ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
              "Beta2PowOut")),
}


def _attr_sig(op):
    return tuple(sorted(
        (k, repr(v)) for k, v in op.attrs.items()
        if not k.startswith("_") and k != "op_callstack"))


def fuse_optimizer_ops(program) -> int:
    """Fuse groups of same-configured optimizer ops in the global
    block. Returns the number of ops fused away. Idempotent (marks the
    program)."""
    if getattr(program, "_opt_fused", False):
        return 0
    block = program.global_block()
    ops = list(block.ops)
    # one recursive (reads, writes) walk per op, shared by every group's
    # interference scan below (groups typically span the whole tail)
    rw = [lowering._op_reads_writes(op) for op in ops]
    rw = [(set(r), set(w)) for r, w in rw]

    groups: Dict[tuple, List[int]] = {}
    for i, op in enumerate(ops):
        if op.type not in _FUSABLE:
            continue
        in_slots, _ = _FUSABLE[op.type]
        if any(len(op.input_names.get(s, [])) != 1 for s in in_slots):
            continue
        lr = op.input_names.get("LearningRate", [""])
        pvar = block._find_var_recursive(op.input_names["Param"][0])
        dtype = str(getattr(pvar, "dtype", "float32"))
        key = (op.type, _attr_sig(op), lr[0], dtype)
        groups.setdefault(key, []).append(i)

    fused_away = 0
    to_remove = set()
    inserts = []  # (position, new op ctor args)
    for key, idxs in groups.items():
        if len(idxs) < 2:
            continue
        op_type, _, lr_name, _ = key
        in_slots, out_slots = _FUSABLE[op_type]
        members = [ops[i] for i in idxs]
        written = set()
        member_reads = set()
        for m in members:
            for names in m.output_names.values():
                written.update(names)
            for names in m.input_names.values():
                member_reads.update(names)
        # safety: ops interleaved with the group must not (a) touch the
        # group's outputs — a reader between two member updates would
        # observe a different schedule after fusion — nor (b) WRITE any
        # member input (a grad rescaled between members would be read
        # post-mutation by the fused op planted at the last position)
        member_ids = {id(m) for m in members}
        safe = True
        for j in range(min(idxs), max(idxs) + 1):
            op = ops[j]
            if id(op) in member_ids:
                continue
            # recursive touch sets: a control-flow op whose sub-block
            # reads/writes group vars is interference too (ADVICE r4 —
            # input/output_arg_names don't surface sub-block accesses)
            reads_j, writes_j = rw[j]
            if (reads_j | writes_j) & written:
                safe = False
                break
            if writes_j & member_reads:
                safe = False
                break
        if not safe:
            continue

        inputs = {slot: [block._find_var_recursive(
            m.input_names[slot][0]) for m in members]
            for slot in in_slots}
        if lr_name:
            inputs["LearningRate"] = [
                block._find_var_recursive(lr_name)]
        outputs = {slot: [block._find_var_recursive(
            m.output_names[slot][0]) for m in members]
            for slot in out_slots}
        attrs = {k: v for k, v in members[0].attrs.items()
                 if not k.startswith("_")}
        inserts.append((max(idxs), "fused_" + op_type, inputs, outputs,
                        attrs))
        to_remove.update(idxs)
        fused_away += len(members) - 1

    if not inserts:
        program._opt_fused = True
        return 0

    # splice: walk ops in order, dropping members and planting each
    # fused op at its group's LAST member position (every grad/decay
    # producer has run by then; the safety check above guarantees no
    # interleaved consumer)
    insert_at = {pos: args for pos, *args in inserts}
    new_ops = []
    for i, op in enumerate(ops):
        if i in insert_at:
            t, ins_, outs_, attrs_ = insert_at[i]
            fused = block.append_op(type=t, inputs=ins_, outputs=outs_,
                                    attrs=attrs_)
            block.ops.pop()  # append_op put it at the tail
            new_ops.append(fused)
            continue
        if i in to_remove:
            continue
        new_ops.append(op)
    block.ops = new_ops
    program._version += 1
    program._opt_fused = True
    return fused_away
