"""User-facing dataset-file authoring API for dataset/PS training.

Reference parity: `python/paddle/fluid/incubate/data_generator/
__init__.py:1` — DataGenerator / MultiSlotDataGenerator /
MultiSlotStringDataGenerator. A user subclass overrides
`generate_sample(line)` (and optionally `generate_batch`); `run_from_
stdin` / `run_from_memory` emit the MultiSlot text line format
(`<ids_num> <id> ...` per slot) that the native feed parser consumes
(core/native/src/data_feed.cc), so generator-authored files train
through `Executor.train_from_dataset`.
"""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32
        self._line_limit = None

    def _set_line_limit(self, line_limit):
        if not isinstance(line_limit, int):
            raise ValueError("line_limit %s must be in int type"
                             % type(line_limit))
        if line_limit < 1:
            raise ValueError("line_limit can not less than 1")
        self._line_limit = line_limit

    def set_batch(self, batch_size):
        """Batch size used by generate_batch grouping."""
        self.batch_size_ = batch_size

    # -- user overrides ---------------------------------------------------
    def generate_sample(self, line):
        """Override: map one raw input line (or None for run_from_memory)
        to an iterator factory yielding [(slot_name, [values...]), ...]."""
        raise NotImplementedError(
            "generate_sample() must be overridden (return a local_iter "
            "function yielding [(name, [feasign, ...]), ...])")

    def generate_batch(self, samples):
        """Override optionally: batch-level post-processing; default
        passes every sample through unchanged."""

        def local_iter():
            for sample in samples:
                yield sample

        return local_iter

    # -- drivers ----------------------------------------------------------
    def _emit(self, sample, out):
        out.write(self._gen_str(sample))

    def _flush_batch(self, batch_samples, out):
        batch_iter = self.generate_batch(batch_samples)
        for sample in batch_iter():
            if sample is not None:
                self._emit(sample, out)

    def run_from_memory(self, out=None):
        """Drive generate_sample(None) until exhausted (debug/bench)."""
        out = out or sys.stdout
        batch = []
        for sample in self.generate_sample(None)():
            if sample is None:
                continue
            batch.append(sample)
            if len(batch) == self.batch_size_:
                self._flush_batch(batch, out)
                batch = []
        if batch:
            self._flush_batch(batch, out)

    def run_from_stdin(self, stdin=None, out=None):
        """Per-line protocol the C++ pipe-command reader drives: each
        stdin line maps through generate_sample to slot lines."""
        stdin = stdin or sys.stdin
        out = out or sys.stdout
        batch = []
        n = 0
        for line in stdin:
            for sample in self.generate_sample(line)():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    self._flush_batch(batch, out)
                    batch = []
            n += 1
            if self._line_limit and n >= self._line_limit:
                break
        if batch:
            self._flush_batch(batch, out)

    def generate_file(self, in_path, out_path):
        """Convenience wrapper: author `out_path` from raw `in_path`
        (the subprocess-free equivalent of `cat in | python gen.py`)."""
        with open(in_path) as fin, open(out_path, "w") as fout:
            self.run_from_stdin(stdin=fin, out=fout)

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")


class MultiSlotDataGenerator(DataGenerator):
    def _gen_str(self, line):
        """[(name, [v, ...]), ...] -> `n v1 .. vn` per slot, one sample
        per line (reference: data_generator/__init__.py:283; consumed by
        data_feed.cc's MultiSlot parser). Also accumulates _proto_info =
        [(name, type), ...] and enforces a stable slot schema."""
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type"
                "Examples: [('words', [1926, 08, 17]), ('label', [1])]")
        output = ""
        if self._proto_info is None:
            self._proto_info = []
            for item in line:
                name, elements = item
                if not isinstance(name, str):
                    raise ValueError("name%s must be in str type"
                                     % type(name))
                if not isinstance(elements, list):
                    raise ValueError("elements%s must be in list type"
                                     % type(elements))
                if not elements:
                    raise ValueError(
                        "the elements of each field can not be empty, "
                        "you need padding it in process().")
                self._proto_info.append((name, "uint64"))
                if output:
                    output += " "
                output += str(len(elements))
                for elem in elements:
                    if isinstance(elem, float):
                        self._proto_info[-1] = (name, "float")
                    elif not isinstance(elem, int):
                        raise ValueError(
                            "the type of element%s must be in int or "
                            "float" % type(elem))
                    output += " " + str(elem)
        else:
            if len(line) != len(self._proto_info):
                raise ValueError(
                    "the complete field set of two given line are "
                    "inconsistent.")
            for index, item in enumerate(line):
                name, elements = item
                if name != self._proto_info[index][0]:
                    raise ValueError(
                        "the field name of two given line are not match: "
                        "require<%s>, get<%s>."
                        % (self._proto_info[index][0], name))
                if output:
                    output += " "
                output += str(len(elements))
                for elem in elements:
                    if self._proto_info[index][1] != "float":
                        if isinstance(elem, float):
                            self._proto_info[index] = (name, "float")
                        elif not isinstance(elem, int):
                            raise ValueError(
                                "the type of element%s must be in int "
                                "or float" % type(elem))
                    output += " " + str(elem)
        return output + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    def _gen_str(self, line):
        """[(name, [str, ...]), ...] -> `n s1 .. sn` per slot
        (reference: data_generator/__init__.py:242)."""
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type"
                "Examples: [('words', ['1926', '08', '17']), "
                "('label', ['1'])]")
        output = ""
        for item in line:
            name, elements = item
            if not isinstance(name, str):
                raise ValueError("name%s must be in str type" % type(name))
            if not isinstance(elements, list):
                raise ValueError("elements%s must be in list type"
                                 % type(elements))
            if output:
                output += " "
            output += str(len(elements))
            for elem in elements:
                if not isinstance(elem, str):
                    raise ValueError(
                        "the type of element%s must be in str type"
                        % type(elem))
                output += " " + elem
        return output + "\n"
