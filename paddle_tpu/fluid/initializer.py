"""Parameter initializers (reference: `python/paddle/fluid/initializer.py`).

Each initializer appends ONE op to the startup program that materializes the
parameter (fill_constant / uniform_random / gaussian_random ...); the
startup block then compiles into a single XLA computation that initializes
every parameter on-device in one launch.
"""
from __future__ import annotations

import math

import numpy as np

from . import framework


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            type="fill_constant", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "value": float(self.value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            type="uniform_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "min": self.low, "max": self.high, "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            type="gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self.loc, "std": self.scale, "seed": self.seed})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            type="truncated_gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self.loc, "std": self.scale, "seed": self.seed})


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return 1, 1
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in, self.fan_out, self.seed = fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    def __call__(self, var, block):
        shape = var.shape
        weight = np.zeros(shape, dtype="float32")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            idx = np.unravel_index(i, shape)
            weight[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        arr = self.value.astype("float64")
        key = ("fp32_values" if var.dtype in ("float32", "float16",
                                              "bfloat16")
               else "int64_values" if var.dtype == "int64"
               else "int32_values" if var.dtype == "int32"
               else "fp32_values")
        block.append_op(
            type="assign_value", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   key: arr.flatten().tolist()})


# paddle-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)
