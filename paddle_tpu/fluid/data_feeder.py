"""DataFeeder (reference: `python/paddle/fluid/data_feeder.py`)."""
from __future__ import annotations

import numpy as np

from .framework import Variable
from ..core.types import to_numpy_dtype


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_list = feed_list
        self.place = place

    def feed(self, iterable):
        """iterable: list of samples, each a tuple aligned with
        feed_list. Returns a feed dict of batched numpy arrays."""
        names = [v.name if isinstance(v, Variable) else v
                 for v in self.feed_list]
        cols = list(zip(*iterable))
        out = {}
        for name, col, var in zip(names, cols, self.feed_list):
            arr = np.stack([np.asarray(s) for s in col])
            if isinstance(var, Variable):
                want = to_numpy_dtype(var.dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
                # match declared trailing shape, e.g. label [N] -> [N,1]
                decl = [d for d in var.shape]
                if (len(decl) == arr.ndim + 1 and decl[-1] == 1):
                    arr = arr[..., None]
            out[name] = arr
        return out

    def feed_parallel(self, iterable, num_places=None):
        return [self.feed(chunk) for chunk in iterable]


def check_variable_and_dtype(input, input_name, expected_dtype, op_name,
                             extra_message=""):
    pass


def check_type(input, input_name, expected_type, op_name):
    pass


def check_dtype(input_dtype, input_name, expected_dtype, op_name):
    pass


def convert_dtype(dtype):
    from ..core.types import normalize_dtype

    return normalize_dtype(dtype)
