"""Optimizers (reference: `python/paddle/fluid/optimizer.py:55-4847`).

`minimize = append_backward + apply_gradients`: gradients come from the
jax.vjp-backed backward section (backward.py); each optimizer then appends
its update op per parameter (kernels in ops/optimizer_ops.py). Accumulators
(moments, beta pows) are persistable scope vars initialized via the startup
program — on TPU the whole step (forward, backward, every param update)
compiles into one XLA executable with donated param buffers.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from . import framework
from .framework import Variable, Parameter, unique_name, in_dygraph_mode
from .backward import append_backward
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .clip import append_gradient_clip_ops
from .regularizer import append_regularization_ops

__all__ = [
    "Optimizer", "SGDOptimizer", "MomentumOptimizer", "AdamOptimizer",
    "AdamaxOptimizer", "AdagradOptimizer", "DecayedAdagradOptimizer",
    "AdadeltaOptimizer", "RMSPropOptimizer", "FtrlOptimizer",
    "LambOptimizer", "LarsMomentumOptimizer", "DGCMomentumOptimizer",
    "DpsgdOptimizer", "ModelAverage", "ExponentialMovingAverage",
    "RecomputeOptimizer", "LookaheadOptimizer", "PipelineOptimizer",
    "GradientMergeOptimizer",
    "SGD", "Momentum", "Adam", "Adamax", "Adagrad", "DecayedAdagrad",
    "Adadelta", "RMSProp", "Ftrl", "Lamb", "LarsMomentum", "Dpsgd",
]


class Optimizer:
    def __init__(self, learning_rate, parameter_list=None,
                 regularization=None, grad_clip=None, name=None,
                 parameters=None, weight_decay=None):
        self._learning_rate = learning_rate
        # `parameters`/`weight_decay` are the 2.0-API spellings
        self._parameter_list = parameter_list if parameter_list is not None \
            else parameters
        if regularization is None and weight_decay is not None:
            from .regularizer import L2Decay

            regularization = weight_decay if not isinstance(
                weight_decay, (int, float)) else L2Decay(float(weight_decay))
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name or unique_name(type(self).__name__)
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._lr_var_per_program = {}
        self.helper = None
        # checkpoint state applied lazily as accumulators are created
        # ("<param_name>_<acc_name>" -> ndarray)
        self._loaded_state: Dict[str, object] = {}

    # -- learning rate -----------------------------------------------------
    def _global_learning_rate(self, program=None):
        program = program or framework.default_main_program()
        if isinstance(self._learning_rate, Variable):
            return self._learning_rate
        key = id(program)
        if key not in self._lr_var_per_program:
            helper = LayerHelper("learning_rate")
            var = helper.create_global_variable(
                name=unique_name("learning_rate"), shape=[1],
                dtype="float32", persistable=True)
            helper.set_variable_initializer(
                var, ConstantInitializer(float(self._learning_rate)))
            self._lr_var_per_program[key] = var
        return self._lr_var_per_program[key]

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        base = self._global_learning_rate()
        plr = getattr(param, "optimize_attr", {}).get("learning_rate", 1.0)
        if plr == 1.0:
            return base
        from .layers import tensor as t

        return t.scale(base, plr, 0.0)

    def current_step_lr(self):
        if isinstance(self._learning_rate, (int, float)):
            return float(self._learning_rate)
        return self._learning_rate

    # -- accumulators ------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        accs = self._accumulators.setdefault(name, {})
        if param.name in accs:
            return accs[param.name]
        if in_dygraph_mode():
            import jax.numpy as jnp

            from .dygraph import base as dy_base

            var = dy_base.create_eager_parameter(
                None, list(shape or param.shape), dtype or "float32",
                ConstantInitializer(fill_value), trainable=False,
                name=unique_name("%s_%s_%s" % (self._name, param.name,
                                               name)))
            loaded = self._loaded_state.pop(
                "%s_%s" % (param.name, name), None)
            if loaded is not None:
                var._assign_raw(jnp.asarray(loaded))
            # eager ZeRO-1 (FLAGS_tpu_sharded_weight_update + an active
            # mesh): accumulators live dim-0-sharded over the mesh from
            # creation; GSPMD partitions the eager update so per-replica
            # optimizer-state HBM is ~1/N — same math, XLA re-gathers
            # params wherever a replicated consumer needs them
            from ..parallel.sharded_update import \
                eager_accumulator_sharding

            sh = eager_accumulator_sharding(
                tuple(var._value().shape))
            if sh is not None:
                import jax

                var._assign_raw(jax.device_put(var._value(), sh))
            accs[param.name] = var
            return var
        helper = LayerHelper(self._name)
        var = helper.create_global_variable(
            name=unique_name("%s_%s_%s" % (self._name, param.name, name)),
            shape=list(shape or param.shape), dtype=dtype or "float32",
            persistable=True)
        helper.set_variable_initializer(var,
                                        ConstantInitializer(fill_value))
        accs[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- core --------------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        if in_dygraph_mode():
            from .dygraph import base as dy_base

            loss.backward()
            params = parameter_list or self._parameter_list
            return [(p, p._grad_tensor()) for p in params
                    if p.trainable and p._grad_tensor() is not None]
        return append_backward(loss, parameter_list or self._parameter_list,
                               no_grad_set)

    def apply_gradients(self, params_grads):
        params_grads = sorted(params_grads, key=lambda pg: pg[0].name)
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        else:
            params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        block = framework.default_main_program().global_block()
        self._create_accumulators(
            block, [pg[0] for pg in params_grads])
        ops = []
        for pg in params_grads:
            ops.append(self._append_optimize_op(block, pg))
        self._finish_update(block, params_grads)
        return ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            return self._minimize_dygraph(loss, parameter_list, no_grad_set)
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    # -- 2.0 dygraph API ---------------------------------------------------
    def step(self):
        """Apply gradients accumulated by loss.backward() (2.0 API)."""
        params = self._parameter_list
        if params is None:
            raise ValueError("step() needs the optimizer constructed with "
                             "parameters=layer.parameters()")
        params_grads = [(p, p._grad_tensor()) for p in params
                        if getattr(p, "trainable", True)
                        and p._grad_tensor() is not None]
        self._dygraph_step(params_grads)

    def clear_grad(self):
        for p in self._parameter_list or []:
            p.clear_gradient()

    # -- dygraph eager path ------------------------------------------------
    def _minimize_dygraph(self, loss, parameter_list=None, no_grad_set=None):
        from .dygraph import base as dy_base

        params = parameter_list or self._parameter_list
        if params is None:
            raise ValueError(
                "dygraph optimizer needs parameter_list (pass "
                "parameter_list=layer.parameters())")
        if not getattr(loss, "_backward_ran", False):
            loss.backward()
        params_grads = [(p, p._grad_tensor()) for p in params
                        if getattr(p, "trainable", True)
                        and p._grad_tensor() is not None]
        self._dygraph_step(params_grads)
        return [], params_grads

    def _dygraph_step(self, params_grads):
        from .dygraph import base as dy_base
        from ..core.selected_rows import SelectedRows

        if self._grad_clip is not None or self.regularization is not None:
            # clip/regularization need dense values; densify sparse grads
            params_grads = [
                (p, dy_base.Tensor(g.to_dense(), stop_gradient=True)
                 if isinstance(g, SelectedRows) else g)
                for p, g in params_grads]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.current_step_lr()
        lr_t = dy_base.to_tensor_value(np.asarray([lr], np.float32))
        for p, g in params_grads:
            if isinstance(g, SelectedRows):
                self._eager_sparse_update(p, g, lr_t)
                continue
            if self.regularization is not None:
                g = self.regularization._eager_apply(p, g)
            self._eager_update(p, g, lr_t)

    def _eager_update(self, param, grad, lr_t):
        raise NotImplementedError(
            "%s: dygraph update not implemented" % type(self).__name__)

    def _eager_sparse_update(self, param, grad_sr, lr_t):
        """SelectedRows grad: optimizers without a dedicated sparse
        kernel densify (reference behavior for most ops; SGD/Adam
        override with true row-wise updates)."""
        from .dygraph import base as dy_base

        self._eager_update(
            param, dy_base.Tensor(grad_sr.to_dense(),
                                  stop_gradient=True), lr_t)

    @staticmethod
    def _sparse_rows_values(grad_sr, dtype):
        """Merged (safe_rows, valid_mask, values) for row-wise kernels.
        Invalid (padding) slots get row index == height, which JAX
        scatter DROPS (out-of-bounds default) — never aliasing row 0."""
        import jax.numpy as jnp

        m = grad_sr.merge()
        rows = jnp.asarray(m.rows)
        valid = rows >= 0
        safe = jnp.where(valid, rows, m.height)
        vals = jnp.where(
            valid.reshape((-1,) + (1,) * (m.values.ndim - 1)),
            jnp.asarray(m.values), 0).astype(dtype)
        return safe, valid, vals

    def clear_gradients(self):
        pass

    def state_dict(self):
        out = {}
        for name, accs in self._accumulators.items():
            for pname, var in accs.items():
                out["%s_%s" % (pname, name)] = var
        return out

    def set_state_dict(self, d):
        """Restore accumulator values (keys "<param_name>_<acc_name>").
        Existing accumulators are overwritten in place; not-yet-created
        ones are applied lazily at creation (reference: optimizer
        state_dict round trip, dygraph/checkpoint.py:98)."""
        import jax.numpy as jnp

        remaining = dict(d)
        for name, accs in self._accumulators.items():
            for pname, var in accs.items():
                key = "%s_%s" % (pname, name)
                if key in remaining:
                    val = remaining.pop(key)
                    if hasattr(var, "_assign_raw"):
                        var._assign_raw(jnp.asarray(np.asarray(val)))
                    else:
                        from ..core.scope import global_scope

                        global_scope().set_var(var.name,
                                               jnp.asarray(np.asarray(val)))
        self._loaded_state.update(remaining)


# ---------------------------------------------------------------------------

class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="sgd",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p]})

    def _eager_sparse_update(self, p, grad_sr, lr_t):
        # reference: sgd_op.h SelectedRows branch — update touched rows
        # only via scatter-add (segment-summed duplicates)
        import jax.numpy as jnp

        safe, valid, vals = self._sparse_rows_values(grad_sr,
                                                     p._val.dtype)
        lr = jnp.reshape(jnp.asarray(lr_t), ()).astype(p._val.dtype)
        p._assign_raw(p._val.at[safe].add(-lr * vals))

    def _eager_update(self, p, g, lr_t):
        from .dygraph import base as dy_base

        out = dy_base.raw_op("sgd",
                             {"Param": [p._value()], "Grad": [g._value()],
                              "LearningRate": [lr_t]}, {},
                             ["ParamOut"])
        p._assign_raw(out[0])


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov})

    def _eager_update(self, p, g, lr_t):
        from .dygraph import base as dy_base

        v = self._add_accumulator("velocity", p)
        out = dy_base.raw_op(
            "momentum",
            {"Param": [p._value()], "Grad": [g._value()],
             "Velocity": [v._value()], "LearningRate": [lr_t]},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov},
            ["ParamOut", "VelocityOut"])
        p._assign_raw(out[0])
        v._assign_raw(out[1])


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, epsilon=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="lars_momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay,
                   "epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, shape=[1],
                                  fill_value=self._beta1)
            self._add_accumulator("beta2_pow_acc", p, shape=[1],
                                  fill_value=self._beta2)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            type=self._op_type(),
            inputs={"Param": [p], "Grad": [g], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs=self._op_attrs(p))

    def _op_type(self):
        return "adam"

    def _op_attrs(self, p):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon}

    def _eager_sparse_update(self, p, grad_sr, lr_t):
        """Lazy-mode sparse Adam (reference: adam_op.h SparseAdamFunctor
        with lazy_mode) — moments and params update only on touched rows;
        beta-pow accumulators advance globally per step."""
        import jax.numpy as jnp

        m1 = self._add_accumulator("moment1", p)
        m2 = self._add_accumulator("moment2", p)
        b1p = self._add_accumulator("beta1_pow_acc", p, shape=[1],
                                    fill_value=self._beta1)
        b2p = self._add_accumulator("beta2_pow_acc", p, shape=[1],
                                    fill_value=self._beta2)
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        safe, valid, vals = self._sparse_rows_values(grad_sr, jnp.float32)
        lr = jnp.reshape(jnp.asarray(lr_t), ()).astype(jnp.float32)

        m1v, m2v = m1._value(), m2._value()
        m1_rows = b1 * m1v[safe] + (1 - b1) * vals
        m2_rows = b2 * m2v[safe] + (1 - b2) * jnp.square(vals)
        b1pf = jnp.reshape(b1p._value(), ()).astype(jnp.float32)
        b2pf = jnp.reshape(b2p._value(), ()).astype(jnp.float32)
        alpha = lr * jnp.sqrt(1 - b2pf * b2) / (1 - b1pf * b1)
        upd = alpha * m1_rows / (jnp.sqrt(m2_rows) + eps)
        mask = valid.reshape((-1,) + (1,) * (vals.ndim - 1))
        pv = p._val
        p._assign_raw(pv.at[safe].add(
            jnp.where(mask, -upd, 0).astype(pv.dtype)))
        m1._assign_raw(m1v.at[safe].set(
            jnp.where(mask, m1_rows, m1v[safe])))
        m2._assign_raw(m2v.at[safe].set(
            jnp.where(mask, m2_rows, m2v[safe])))
        b1p._assign_raw(b1p._value() * b1)
        b2p._assign_raw(b2p._value() * b2)

    def _eager_update(self, p, g, lr_t):
        from .dygraph import base as dy_base

        m1 = self._add_accumulator("moment1", p)
        m2 = self._add_accumulator("moment2", p)
        b1p = self._add_accumulator("beta1_pow_acc", p, shape=[1],
                                    fill_value=self._beta1)
        b2p = self._add_accumulator("beta2_pow_acc", p, shape=[1],
                                    fill_value=self._beta2)
        out = dy_base.raw_op(
            self._op_type(),
            {"Param": [p._value()], "Grad": [g._value()],
             "Moment1": [m1._value()], "Moment2": [m2._value()],
             "Beta1Pow": [b1p._value()], "Beta2Pow": [b2p._value()],
             "LearningRate": [lr_t]},
            self._op_attrs(p),
            ["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
             "Beta2PowOut"])
        p._assign_raw(out[0])
        m1._assign_raw(out[1])
        m2._assign_raw(out[2])
        b1p._assign_raw(out[3])
        b2p._assign_raw(out[4])


class AdamWOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._coeff = weight_decay

    def _op_type(self):
        return "adamw"

    def _op_attrs(self, p):
        a = super()._op_attrs(p)
        a["coeff"] = self._coeff
        return a


class LambOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 exclude_from_weight_decay_fn=None, **kwargs):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, **kwargs)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _op_type(self):
        return "lamb"

    def _op_attrs(self, p):
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon, "weight_decay": wd}


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, shape=[1],
                                  fill_value=self._beta1)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="adamax",
            inputs={"Param": [p], "Grad": [g],
                    "Moment": [self._get_accumulator("moment", p)],
                    "InfNorm": [self._get_accumulator("inf_norm", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p],
                     "MomentOut": [self._get_accumulator("moment", p)],
                     "InfNormOut": [self._get_accumulator("inf_norm", p)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})

    def _finish_update(self, block, params_grads):
        for p, g in params_grads:
            b1p = self._get_accumulator("beta1_pow_acc", p)
            block.append_op(
                type="scale", inputs={"X": [b1p]}, outputs={"Out": [b1p]},
                attrs={"scale": self._beta1, "bias": 0.0,
                       "bias_after_scale": True})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [m],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"epsilon": self._epsilon})


class DecayedAdagradOptimizer(AdagradOptimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, epsilon=epsilon, **kwargs)
        self._decay = decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [m],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator("avg_squared_grad", p)
        asu = self._get_accumulator("avg_squared_update", p)
        return block.append_op(
            type="adadelta",
            inputs={"Param": [p], "Grad": [g], "AvgSquaredGrad": [asg],
                    "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [p], "AvgSquaredGradOut": [asg],
                     "AvgSquaredUpdateOut": [asu]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum_acc", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        ms = self._get_accumulator("mean_square", p)
        mom = self._get_accumulator("momentum_acc", p)
        ins = {"Param": [p], "Grad": [g], "MeanSquare": [ms],
               "Moment": [mom],
               "LearningRate": [self._create_param_lr(param_and_grad)]}
        outs = {"ParamOut": [p], "MeanSquareOut": [ms], "MomentOut": [mom]}
        if self._centered:
            mg = self._get_accumulator("mean_grad", p)
            ins["MeanGrad"] = [mg]
            outs["MeanGradOut"] = [mg]
        return block.append_op(
            type="rmsprop", inputs=ins, outputs=outs,
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        return block.append_op(
            type="ftrl",
            inputs={"Param": [p], "Grad": [g], "SquaredAccumulator": [sq],
                    "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})


class DpsgdOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="dpsgd",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p]},
            attrs={"clip": self._clip, "batch_size": self._batch_size,
                   "sigma": self._sigma})


def normalize_dgc_cfg(momentum, sparsity, rampup_begin_step):
    """Single home for the DGC config shape: the reference passes
    sparsity as a rampup LIST; the final value is the steady-state
    sparsity the dgc op runs at."""
    if isinstance(sparsity, (list, tuple)):
        sparsity = sparsity[-1]
    return {
        "momentum": float(momentum),
        "sparsity": float(sparsity),
        "rampup_begin_step": float(rampup_begin_step),
    }


class DGCMomentumOptimizer(MomentumOptimizer):
    """Deep Gradient Compression momentum (reference:
    `optimizers/dgc_momentum_op.cc` + `python optimizer.py:1149`): marks
    the program so `fleet.transpile_collective` plants the `dgc` op
    (momentum-corrected top-k sparsification with U/V residual
    accumulators) before each gradient's allreduce. The local momentum
    op still runs (reference dgc_momentum = momentum before
    rampup_begin_step; afterwards the dgc op's own correction
    dominates and the summed masked grads flow through it)."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=None, use_nesterov=False,
                 num_trainers=None, regularization=None, grad_clip=None,
                 name=None, **kwargs):
        super().__init__(learning_rate, momentum,
                         use_nesterov=use_nesterov,
                         regularization=regularization,
                         grad_clip=grad_clip, name=name, **kwargs)
        self._step_counter = None
        self._dgc_cfg = normalize_dgc_cfg(
            momentum, sparsity if sparsity else [0.75],
            rampup_begin_step)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.block.program._dgc_cfg = self._dgc_cfg
        return super().minimize(loss, startup_program, parameter_list,
                                no_grad_set)

    def _append_optimize_op(self, block, param_and_grad):
        from .layers import tensor as _tensor

        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        if self._step_counter is None:
            self._step_counter = _tensor.create_global_var(
                [1], 0.0, "float32", persistable=True,
                name=unique_name("dgc_opt_step"))
        out = block.append_op(
            type="dgc_momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                    "LearningRate": [
                        self._create_param_lr(param_and_grad)],
                    "CurrentStep": [self._step_counter]},
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov,
                   "rampup_begin_step":
                       self._dgc_cfg["rampup_begin_step"]})
        return out

    def _finish_update(self, block, params_grads):
        # bump the shared step counter once per executed step
        if self._step_counter is not None:
            block.append_op(
                type="increment",
                inputs={"X": [self._step_counter]},
                outputs={"Out": [self._step_counter]},
                attrs={"step": 1.0})
        return super()._finish_update(block, params_grads)


class _ParamSwapMixin:
    """Shared apply()/restore() machinery: swap live parameter values in
    the scope with computed replacements, host-side (the swap happens
    between steps, so no jit interaction)."""

    def _swap_in(self, replacements):
        from ..core.scope import global_scope

        scope = global_scope()
        self._saved = {}
        for name, new in replacements.items():
            cur = scope.find_var(name)
            if cur is None:
                continue
            self._saved[name] = cur
            scope.set_var(name, np.asarray(new).astype(
                np.asarray(cur).dtype))

    def restore(self, executor=None):
        from ..core.scope import global_scope

        scope = global_scope()
        for name, old in getattr(self, "_saved", {}).items():
            scope.set_var(name, old)
        self._saved = {}

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._swap_in(self._replacements())
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)

        return ctx()


class ModelAverage(Optimizer, _ParamSwapMixin):
    """Windowed running average of params (reference: optimizer.py:3075).
    Construct AFTER minimize(): accumulation ops (sum_1/2/3 rotation +
    counters, vectorized with a masked rotate instead of the reference's
    conditional blocks — XLA-friendly) are appended to the current main
    program; apply() swaps params to the windowed average."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kwargs):
        super().__init__(0.0, **kwargs)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._accum = {}  # pname -> dict of accumulator var names
        program = framework.default_main_program()
        block = program.global_block()
        helper = LayerHelper("model_average")
        params = [p for p in block.all_parameters() if p.trainable]
        for p in params:
            acc = {}
            for nm, init in (("sum_1", 0.0), ("sum_2", 0.0),
                             ("sum_3", 0.0)):
                v = helper.create_global_variable(
                    name=unique_name("%s_%s" % (p.name, nm)),
                    shape=list(p.shape), dtype=p.dtype, persistable=True)
                helper.set_variable_initializer(
                    v, ConstantInitializer(init))
                acc[nm] = v
            for nm in ("num_accumulates", "old_num_accumulates"):
                v = helper.create_global_variable(
                    name=unique_name("%s_%s" % (p.name, nm)),
                    shape=[1], dtype="float32", persistable=True)
                helper.set_variable_initializer(
                    v, ConstantInitializer(0.0))
                acc[nm] = v
            self._accum[p.name] = {k: v.name for k, v in acc.items()}
            self._append_accumulate(block, p, acc)

    def _append_accumulate(self, block, p, acc):
        def op(type_, ins, outs, attrs=None):
            block.append_op(type=type_, inputs=ins, outputs=outs,
                            attrs=attrs or {})

        s1, s2, s3 = acc["sum_1"], acc["sum_2"], acc["sum_3"]
        num, old = acc["num_accumulates"], acc["old_num_accumulates"]
        # sum_1 += p ; num += 1
        op("elementwise_add", {"X": [s1], "Y": [p]}, {"Out": [s1]},
           {"axis": -1})
        one = block.create_var(name=unique_name("ma_one"), shape=[1],
                               dtype="float32")
        op("fill_constant", {}, {"Out": [one]},
           {"shape": [1], "dtype": "float32", "value": 1.0})
        op("elementwise_add", {"X": [num], "Y": [one]}, {"Out": [num]},
           {"axis": -1})
        # masked rotate when num >= max_window (reference
        # average_accumulates_op.h:103-106):
        #   sum_3 <- sum_1 + sum_2 ; sum_1 <- 0 ; sum_2 <- 0
        #   old_num <- num (REPLACED, not accumulated) ; num <- 0
        # old_num must be replaced: it counts only the windows whose
        # sums are retained in sum_3; accumulating it would make the
        # apply() denominator count discarded windows, decaying the
        # averaged weights toward zero past 3 rotations.
        thresh = block.create_var(name=unique_name("ma_thr"), shape=[1],
                                  dtype="float32")
        op("fill_constant", {}, {"Out": [thresh]},
           {"shape": [1], "dtype": "float32",
            "value": float(self.max_average_window)})
        flag_b = block.create_var(name=unique_name("ma_flagb"),
                                  shape=[1], dtype="bool")
        op("greater_equal", {"X": [num], "Y": [thresh]},
           {"Out": [flag_b]})
        flag = block.create_var(name=unique_name("ma_flag"), shape=[1],
                                dtype="float32")
        op("cast", {"X": [flag_b]}, {"Out": [flag]},
           {"in_dtype": "bool", "out_dtype": "float32"})
        keep = block.create_var(name=unique_name("ma_keep"), shape=[1],
                                dtype="float32")
        op("scale", {"X": [flag]}, {"Out": [keep]},
           {"scale": -1.0, "bias": 1.0, "bias_after_scale": True})

        def blend(dst, a, b):
            # dst = flag*a + keep*b  (elementwise, broadcasting [1])
            ta = block.create_var(name=unique_name("ma_t"),
                                  shape=p.shape, dtype=p.dtype)
            tb = block.create_var(name=unique_name("ma_t"),
                                  shape=p.shape, dtype=p.dtype)
            op("elementwise_mul", {"X": [a], "Y": [flag]},
               {"Out": [ta]}, {"axis": -1})
            op("elementwise_mul", {"X": [b], "Y": [keep]},
               {"Out": [tb]}, {"axis": -1})
            op("elementwise_add", {"X": [ta], "Y": [tb]},
               {"Out": [dst]}, {"axis": -1})

        s12 = block.create_var(name=unique_name("ma_s12"),
                               shape=p.shape, dtype=p.dtype)
        op("elementwise_add", {"X": [s1], "Y": [s2]}, {"Out": [s12]},
           {"axis": -1})
        blend(s3, s12, s3)
        # sum_1 <- keep * sum_1 ; sum_2 <- keep * sum_2
        op("elementwise_mul", {"X": [s1], "Y": [keep]}, {"Out": [s1]},
           {"axis": -1})
        op("elementwise_mul", {"X": [s2], "Y": [keep]}, {"Out": [s2]},
           {"axis": -1})
        # old_num <- flag*num + keep*old_num ; num <- keep*num
        tn = block.create_var(name=unique_name("ma_t"), shape=[1],
                              dtype="float32")
        to = block.create_var(name=unique_name("ma_t"), shape=[1],
                              dtype="float32")
        op("elementwise_mul", {"X": [num], "Y": [flag]}, {"Out": [tn]},
           {"axis": -1})
        op("elementwise_mul", {"X": [old], "Y": [keep]}, {"Out": [to]},
           {"axis": -1})
        op("elementwise_add", {"X": [tn], "Y": [to]}, {"Out": [old]},
           {"axis": -1})
        op("elementwise_mul", {"X": [num], "Y": [keep]}, {"Out": [num]},
           {"axis": -1})

    def minimize(self, *a, **k):
        raise NotImplementedError(
            "ModelAverage wraps an inner optimizer; use apply()")

    def _replacements(self):
        from ..core.scope import global_scope

        scope = global_scope()
        out = {}
        for pname, acc in self._accum.items():
            s = sum(np.asarray(scope.find_var(acc[k]))
                    for k in ("sum_1", "sum_2", "sum_3"))
            n = (float(np.asarray(scope.find_var(
                acc["num_accumulates"])).ravel()[0])
                + float(np.asarray(scope.find_var(
                    acc["old_num_accumulates"])).ravel()[0]))
            if n > 0:
                out[pname] = s / n
        return out


class ExponentialMovingAverage(_ParamSwapMixin):
    """EMA of params (reference: optimizer.py:3384): update() appends
    shadow-update ops; apply() swaps params to the bias-corrected EMAs
    (EMA_t / (1 - decay^t)); restore() puts the originals back."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or "ema"
        self._shadow = {}
        self._step_var = None

    def update(self):
        program = framework.default_main_program()
        block = program.global_block()
        helper = LayerHelper(self._name)
        if self._step_var is None:
            step = helper.create_global_variable(
                name=unique_name(self._name + "_step"), shape=[1],
                dtype="float32", persistable=True)
            helper.set_variable_initializer(step,
                                            ConstantInitializer(0.0))
            self._step_var = step
            one = block.create_var(name=unique_name("ema_one"),
                                   shape=[1], dtype="float32")
            block.append_op(type="fill_constant", inputs={},
                            outputs={"Out": [one]},
                            attrs={"shape": [1], "dtype": "float32",
                                   "value": 1.0})
            block.append_op(type="elementwise_add",
                            inputs={"X": [step], "Y": [one]},
                            outputs={"Out": [step]}, attrs={"axis": -1})
        for p in block.all_parameters():
            if not p.trainable:
                continue
            if p.name not in self._shadow:
                shadow = helper.create_global_variable(
                    name=unique_name(p.name + "_ema"), shape=list(p.shape),
                    dtype=p.dtype, persistable=True)
                helper.set_variable_initializer(shadow,
                                                ConstantInitializer(0.0))
                self._shadow[p.name] = shadow
            shadow = self._shadow[p.name]
            # shadow = decay*shadow + (1-decay)*param
            block.append_op(
                type="scale", inputs={"X": [shadow]},
                outputs={"Out": [shadow]},
                attrs={"scale": self._decay, "bias": 0.0,
                       "bias_after_scale": True})
            tmp = block.create_var(name=unique_name("ema_tmp"),
                                   shape=p.shape, dtype=p.dtype)
            block.append_op(
                type="scale", inputs={"X": [p]}, outputs={"Out": [tmp]},
                attrs={"scale": 1.0 - self._decay, "bias": 0.0,
                       "bias_after_scale": True})
            block.append_op(type="elementwise_add",
                            inputs={"X": [shadow], "Y": [tmp]},
                            outputs={"Out": [shadow]}, attrs={"axis": -1})

    def _replacements(self):
        from ..core.scope import global_scope

        scope = global_scope()
        t = 0.0
        if self._step_var is not None:
            v = scope.find_var(self._step_var.name)
            if v is not None:
                t = float(np.asarray(v).ravel()[0])
        # bias correction: EMA_t / (1 - decay^t) (reference docstring)
        corr = 1.0 - self._decay ** t if t > 0 else 1.0
        out = {}
        for pname, shadow in self._shadow.items():
            sv = scope.find_var(shadow.name)
            if sv is not None:
                out[pname] = np.asarray(sv) / max(corr, 1e-12)
        return out


class RecomputeOptimizer(Optimizer):
    """Activation checkpointing (reference: optimizer.py:4485). TPU-native:
    gradient rematerialisation is jax.checkpoint applied during the vjp
    section; checkpoint vars are recorded on the backward op so lowering
    can segment the forward into remat blocks."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = self._optimizer.minimize(loss, startup_program,
                                          parameter_list, no_grad_set)
        if self._checkpoints:
            block = loss.block
            for op in block.ops:
                if op.type == "backward":
                    op.attrs["checkpoints"] = [
                        v.name if isinstance(v, Variable) else v
                        for v in self._checkpoints]
        return result


class LookaheadOptimizer:
    """Lookahead (reference: optimizer.py:4777): keeps a persistable slow
    copy of every parameter; every k steps the slow weights interpolate
    toward the fast weights (slow += alpha*(fast-slow)) and the fast
    weights snap back to the slow ones. Implemented with a step counter
    plus one `lookahead_step` op per parameter appended after the inner
    optimizer's updates."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        assert 0.0 <= alpha <= 1.0, alpha
        assert k >= 1, k
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            raise NotImplementedError(
                "LookaheadOptimizer is static-graph only (dygraph loss "
                "has no program to append the slow-weight ops to)")
        result = self.inner_optimizer.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)
        block = loss.block
        startup = startup_program or framework.default_startup_program()
        helper = LayerHelper("lookahead")

        counter = helper.create_global_variable(
            name=unique_name("lookahead_counter"), shape=[1],
            dtype="int64", persistable=True)
        helper.set_variable_initializer(counter, ConstantInitializer(0))
        block.append_op(type="increment", inputs={"X": [counter]},
                        outputs={"Out": [counter]}, attrs={"step": 1.0})

        for param, _ in result[1]:
            slow = helper.create_global_variable(
                name=unique_name(param.name + "@SLOW"),
                shape=param.shape, dtype=param.dtype, persistable=True)
            # slow weights start as a copy of the (initialized) params
            startup.global_block().create_var(
                name=slow.name, shape=slow.shape, dtype=slow.dtype,
                persistable=True)
            startup.global_block().append_op(
                type="assign", inputs={"X": [param.name]},
                outputs={"Out": [slow.name]}, attrs={})
            block.append_op(
                type="lookahead_step",
                inputs={"Param": [param], "SlowParam": [slow],
                        "Step": [counter]},
                outputs={"ParamOut": [param], "SlowParamOut": [slow]},
                attrs={"alpha": float(self.alpha), "k": int(self.k)})
        return result


class GradientMergeOptimizer:
    """k-step gradient accumulation (reference: gradient_merge strategy,
    `framework/ir/multi_batch_merge_pass.cc`; fleet 2.0 GradientMerge
    meta-optimizer). Grads accumulate into persistable buffers and the
    optimizer section runs only every k-th call (lowering executes it
    under lax.cond — see lowering._run_gradient_merge)."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        assert k_steps >= 1, k_steps
        self.inner_optimizer = inner_optimizer
        self.k_steps = k_steps
        self.avg = avg

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            raise NotImplementedError(
                "GradientMergeOptimizer is static-graph only; in dygraph "
                "accumulate grads by calling backward() k times before "
                "minimize (grads sum until clear_gradients)")
        result = self.inner_optimizer.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)
        if self.k_steps <= 1:
            return result
        block = loss.block
        bops = [op for op in block.ops if op.type == "backward"]
        assert bops, "gradient merge requires a backward section"
        helper = LayerHelper("gradient_merge")
        acc_map = {}
        for param, grad in result[1]:
            acc = helper.create_global_variable(
                name=unique_name(param.name + "@GRAD@MERGE"),
                shape=param.shape, dtype="float32", persistable=True)
            helper.set_variable_initializer(acc, ConstantInitializer(0.0))
            acc_map[grad.name] = acc.name
        counter = helper.create_global_variable(
            name=unique_name("gradient_merge_counter"), shape=[1],
            dtype="int64", persistable=True)
        helper.set_variable_initializer(counter, ConstantInitializer(0))
        bops[0].attrs["gradient_merge"] = {
            "k_steps": int(self.k_steps), "avg": bool(self.avg),
            "acc_map": acc_map, "counter": counter.name,
        }
        # declare the accumulators/counter on the backward op so the
        # dataflow analysis (lowering.analyze_block) threads them as
        # mutable scope state
        extra = list(acc_map.values()) + [counter.name]
        bops[0].input_names["GradMergeState"] = extra
        bops[0].output_names["GradMergeState"] = extra
        return result


class PipelineOptimizer:
    """Pipeline-parallel program splitter (reference: optimizer.py:3634 +
    pipeline_trainer.cc section_worker.cc:82). The program is cut at
    `cut_list` variables into per-stage subprograms; lowering dispatches
    to the paddle_tpu.parallel.pipeline GPipe engine (shard_map over a
    'pp' mesh axis, lax.scan fill-drain with ppermute boundary handoff,
    num_microbatches gradient accumulation)."""

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0, num_microbatches=1):
        self._optimizer = optimizer
        self._cut_list = cut_list or []
        self._num_microbatches = num_microbatches

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = self._optimizer.minimize(loss, startup_program,
                                          parameter_list, no_grad_set)
        cut_names = []
        for cut in self._cut_list:
            vars_ = cut if isinstance(cut, (list, tuple)) else [cut]
            for v in vars_:
                cut_names.append(v.name if isinstance(v, Variable)
                                 else str(v))
        program = loss.block.program
        program._pipeline_cfg = {
            "cut_names": cut_names,
            "n_micro": int(self._num_microbatches),
        }
        return result


# paddle 2.0-style aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
Dpsgd = DpsgdOptimizer
