"""Dygraph -> static capture (reference: `python/paddle/fluid/dygraph/jit.py`
TracedLayer over ProgramDescTracer, and the @declarative AST transformer
suite in dygraph_to_static/).

TPU-native: jax.jit already compiles eager code; TracedLayer wraps a Layer
into a jitted callable + saved weights rather than re-tracing into a
ProgramDesc.
"""
from __future__ import annotations

import numpy as np

from . import base
from .layers import Layer


class TracedLayer:
    def __init__(self, layer, fn):
        self._layer = layer
        self._fn = fn

    @staticmethod
    def trace(layer, inputs):
        import jax

        params = {p.name: p._val for p in layer.parameters()}

        def fn(param_vals, *args):
            for p in layer.parameters():
                p._assign_raw(param_vals[p.name])
            outs = layer(*[base.to_variable(a) for a in args])
            if isinstance(outs, (list, tuple)):
                return [o._val for o in outs]
            return [outs._val]

        outs = layer(*inputs)
        traced = TracedLayer(layer, fn)
        return outs, traced

    def __call__(self, *inputs):
        params = {p.name: p._val for p in self._layer.parameters()}
        arrs = [i._val if isinstance(i, base.Tensor) else np.asarray(i)
                for i in inputs]
        outs = self._fn(params, *arrs)
        return [base.wrap_raw(o) for o in outs]

    def save_inference_model(self, dirname, feed=None, fetch=None):
        from ..io import _save_dict

        _save_dict(dirname, {p.name: np.asarray(p._val)
                             for p in self._layer.parameters()})


def declarative(fn):
    """@declarative: in this framework eager code is already jit-friendly;
    returns the function unchanged (jax.jit applied at call sites)."""
    return fn
