"""Dygraph -> static jit API (reference:
`python/paddle/fluid/dygraph/jit.py` — @declarative, TracedLayer,
jit save/load over `ProgramDescTracer`
`imperative/jit/program_desc_tracer.h:47`).

TPU-native: capture replays the eager network through the static front
end (see dygraph_to_static/), producing a real `Program` that lowers to
ONE XLA computation and round-trips through `save_inference_model`.
"""
from __future__ import annotations

import numpy as np

from . import base
from .dygraph_to_static import (
    ProgramTranslator, StaticFunction, capture_program,
)
from .dygraph_to_static.ast_transformer import convert_to_static
from .layers import Layer


def declarative(function=None):
    """Decorator converting a dygraph function (or Layer method) into a
    per-signature-cached static Program execution."""
    if function is None:
        return declarative
    if isinstance(function, StaticFunction):
        return function
    return StaticFunction(function)


# paddle 2.x name
to_static = declarative


class TracedLayer:
    """Static capture of a dygraph Layer from example inputs (reference:
    dygraph/jit.py TracedLayer)."""

    def __init__(self, layer, concrete):
        self._layer = layer
        self._concrete = concrete

    @staticmethod
    def trace(layer, inputs):
        if not isinstance(layer, Layer):
            raise TypeError("TracedLayer.trace expects a Layer")
        inputs = list(inputs)
        outs = layer(*inputs)  # eager pass: actual outputs for the caller
        fwd = type(layer).forward
        if isinstance(fwd, StaticFunction):
            concrete = fwd.__get__(layer).concrete_program(*inputs)
        else:
            fn = convert_to_static(fwd)
            concrete = capture_program(fn, tuple([layer] + inputs))
        return outs, TracedLayer(layer, concrete)

    def __call__(self, *inputs):
        outs = self._concrete.run(list(inputs))
        return outs if isinstance(outs, (list, tuple)) else [outs]

    @property
    def program(self):
        return self._concrete.main

    def save_inference_model(self, dirname, feed=None, fetch=None):
        from .. import io
        from ..executor import Executor

        feed_names = self._concrete.feed_names
        fetch_vars = list(self._concrete.fetch_vars)
        if feed is not None:
            feed_names = [feed_names[i] for i in feed]
        if fetch is not None:
            fetch_vars = [fetch_vars[i] for i in fetch]
        self._concrete.ctx.refresh_scope()
        io.save_inference_model(dirname, feed_names, fetch_vars,
                                Executor(),
                                main_program=self._concrete.main)


def save(layer, model_path, input_spec=None):
    """paddle.jit.save: capture `layer.forward` (via its @declarative
    cache when present) and export an inference model directory."""
    from .. import io
    from ..executor import Executor

    if input_spec is None:
        raise ValueError("jit.save needs input_spec (example Tensors or "
                         "hapi-style Input specs)")
    example = []
    for spec in input_spec:
        if isinstance(spec, base.Tensor) or isinstance(spec, np.ndarray):
            example.append(spec)
        else:  # Input-like: shape/dtype spec (batch dim None -> 1)
            shape = [1 if s is None else int(s) for s in spec.shape]
            example.append(np.zeros(shape, dtype=str(spec.dtype)))
    fwd = type(layer).forward
    if isinstance(fwd, StaticFunction):
        concrete = fwd.__get__(layer).concrete_program(*example)
    else:
        fn = convert_to_static(fwd)
        concrete = capture_program(fn, tuple([layer] + example))
    concrete.ctx.refresh_scope()
    io.save_inference_model(model_path, concrete.feed_names,
                            list(concrete.fetch_vars), Executor(),
                            main_program=concrete.main)


class _LoadedLayer(Layer):
    """Callable returned by jit.load: runs the saved inference program."""

    def __init__(self, model_path):
        super().__init__()
        from .. import io
        from ..executor import Executor

        self._exe = Executor()
        (self._program, self._feed_names,
         self._fetch_vars) = io.load_inference_model(model_path, self._exe)
        # forward() re-feeds caller-owned eager tensor buffers: never
        # donate them (lowering._feed_donate opt-out); the feed list
        # rides along for tpu-lint's donation checker (see
        # ConcreteProgram)
        self._program._feed_donate = False
        self._program._feed_names = list(self._feed_names)

    def forward(self, *inputs):
        feed = {}
        for name, a in zip(self._feed_names, inputs):
            feed[name] = a._val if isinstance(a, base.Tensor) \
                else np.asarray(a)
        outs = self._exe.run(self._program, feed=feed,
                             fetch_list=list(self._fetch_vars),
                             return_numpy=False)
        outs = [base.wrap_raw(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def __call__(self, *inputs):
        # bypass Layer.__call__ hook plumbing requiring dygraph mode
        return self.forward(*inputs)


def load(model_path):
    return _LoadedLayer(model_path)
