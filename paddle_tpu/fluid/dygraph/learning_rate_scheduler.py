"""Dygraph LR schedulers (reference:
`python/paddle/fluid/dygraph/learning_rate_scheduler.py`). Each is a python
object whose __call__/step() yields the current lr; optimizers accept one as
learning_rate."""
from __future__ import annotations

import math

import numpy as np


class LearningRateDecay:
    def __init__(self, begin=0, step=1, dtype="float32"):
        self.step_num = begin
        self.step_size = step
        self.dtype = dtype

    def step(self):
        self.step_num += self.step_size

    def __call__(self):
        lr = self.get_lr()
        self.step()
        return float(lr)

    def get_lr(self):
        raise NotImplementedError

    # optimizers call float() on learning_rate each step
    def __float__(self):
        return float(self.get_lr())


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 dtype="float32", learning_rate=1.0):
        super().__init__(begin, step, dtype)
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        self.learning_rate = learning_rate

    def get_lr(self):
        step = max(self.step_num, 1)
        a = step ** -0.5
        b = step * self.warmup_steps ** -1.5
        return self.learning_rate * (self.d_model ** -0.5) * min(a, b)


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.boundaries = boundaries
        self.values = values

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.step_num < b:
                return v
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def get_lr(self):
        r = self.step_num / self.decay_steps
        if self.staircase:
            r = math.floor(r)
        return self.learning_rate * math.exp(-self.decay_rate * r)


class ExponentialDecay(NaturalExpDecay):
    def get_lr(self):
        r = self.step_num / self.decay_steps
        if self.staircase:
            r = math.floor(r)
        return self.learning_rate * (self.decay_rate ** r)


class InverseTimeDecay(NaturalExpDecay):
    def get_lr(self):
        r = self.step_num / self.decay_steps
        if self.staircase:
            r = math.floor(r)
        return self.learning_rate / (1 + self.decay_rate * r)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=0.0001,
                 power=1.0, cycle=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.end_learning_rate = end_learning_rate
        self.power = power
        self.cycle = cycle

    def get_lr(self):
        step = self.step_num
        decay_steps = self.decay_steps
        if self.cycle and step > 0:
            decay_steps = decay_steps * math.ceil(step / decay_steps)
        step = min(step, decay_steps)
        frac = (1 - step / decay_steps) ** self.power
        return ((self.learning_rate - self.end_learning_rate) * frac
                + self.end_learning_rate)


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def get_lr(self):
        epoch = self.step_num // self.step_each_epoch
        return self.learning_rate * 0.5 * (
            math.cos(epoch * math.pi / self.epochs) + 1)


class LinearLrWarmup(LearningRateDecay):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 begin=1, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.lr = learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr

    def get_lr(self):
        if self.step_num < self.warmup_steps:
            return (self.start_lr + (self.end_lr - self.start_lr)
                    * self.step_num / self.warmup_steps)
        base = self.lr
        return float(base.get_lr() if isinstance(base, LearningRateDecay)
                     else base)


class ReduceLROnPlateau(LearningRateDecay):
    def __init__(self, learning_rate, mode="min", decay_rate=0.1,
                 patience=10, verbose=False, threshold=1e-4,
                 threshold_mode="rel", cooldown=0, min_lr=0, eps=1e-8,
                 dtype="float32"):
        super().__init__(0, 1, dtype)
        self.lr = float(learning_rate)
        self.mode = mode
        self.decay_rate = decay_rate
        self.patience = patience
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.threshold = threshold
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0

    def get_lr(self):
        return self.lr

    def step(self, metric=None):
        if metric is None:
            return
        m = float(np.asarray(metric).reshape(-1)[0])
        better = (self.best is None
                  or (self.mode == "min" and m < self.best - self.threshold)
                  or (self.mode == "max" and m > self.best + self.threshold))
        if better:
            self.best = m
            self.num_bad = 0
        elif self.cooldown_counter > 0:
            self.cooldown_counter -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self.lr = max(self.lr * self.decay_rate, self.min_lr)
                self.cooldown_counter = self.cooldown
                self.num_bad = 0
