"""Dygraph layer library (reference: `python/paddle/fluid/dygraph/nn.py` —
Conv2D, Linear, BatchNorm, Embedding, LayerNorm, Pool2D, Dropout, ...)."""
from __future__ import annotations

import numpy as np

from .. import framework
from ..initializer import ConstantInitializer, NormalInitializer
from ..param_attr import ParamAttr
from . import base
from .base import trace_op
from .layers import Layer


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[input_dim, output_dim], attr=param_attr, dtype=dtype)
        self.bias = self.create_parameter(
            shape=[output_dim], attr=bias_attr, dtype=dtype, is_bias=True)
        self._act = act

    def forward(self, input):
        out = trace_op("matmul", {"X": [input], "Y": [self.weight]},
                       {"transpose_X": False, "transpose_Y": False,
                        "alpha": 1.0}, ["Out"])[0]
        if self.bias is not None:
            out = trace_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]},
                           {"axis": out.ndim - 1}, ["Out"])[0]
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {}, ["Out"])[0]
        return out


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__()
        filter_size = ([filter_size, filter_size]
                       if isinstance(filter_size, int)
                       else list(filter_size))
        self._stride = ([stride, stride] if isinstance(stride, int)
                        else list(stride))
        self._padding = ([padding, padding] if isinstance(padding, int)
                         else list(padding))
        self._dilation = ([dilation, dilation] if isinstance(dilation, int)
                          else list(dilation))
        self._groups = groups
        self._act = act
        fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
        std = (2.0 / fan_in) ** 0.5
        self.weight = self.create_parameter(
            shape=[num_filters, num_channels // groups] + filter_size,
            attr=param_attr, dtype=dtype,
            default_initializer=NormalInitializer(0.0, std))
        self.bias = self.create_parameter(
            shape=[num_filters], attr=bias_attr, dtype=dtype, is_bias=True)

    def forward(self, input):
        out = trace_op("conv2d",
                       {"Input": [input], "Filter": [self.weight]},
                       {"strides": self._stride, "paddings": self._padding,
                        "dilations": self._dilation, "groups": self._groups},
                       ["Output"])[0]
        if self.bias is not None:
            out = trace_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]}, {"axis": 1},
                           ["Out"])[0]
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {}, ["Out"])[0]
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True):
        super().__init__()
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size, pool_size] if isinstance(pool_size, int)
            else list(pool_size),
            "strides": [pool_stride, pool_stride]
            if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding, pool_padding]
            if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        }

    def forward(self, input):
        return trace_op("pool2d", {"X": [input]}, dict(self._attrs),
                        ["Out"])[0]


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__()
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_layout = data_layout
        self._use_global_stats = use_global_stats
        self._act = act
        self.weight = self.create_parameter(
            shape=[num_channels], attr=param_attr, dtype=dtype,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter(
            shape=[num_channels], attr=bias_attr, dtype=dtype, is_bias=True)
        self._mean = base.create_eager_parameter(
            None, [num_channels], dtype, ConstantInitializer(0.0),
            trainable=False, name=moving_mean_name)
        self._variance = base.create_eager_parameter(
            None, [num_channels], dtype, ConstantInitializer(1.0),
            trainable=False, name=moving_variance_name)
        self.register_buffer("_mean_buf", self._mean)
        self.register_buffer("_var_buf", self._variance)

    def forward(self, input):
        outs = trace_op(
            "batch_norm",
            {"X": [input], "Scale": [self.weight], "Bias": [self.bias],
             "Mean": [self._mean], "Variance": [self._variance]},
            {"momentum": self._momentum, "epsilon": self._epsilon,
             "is_test": not self.training,
             "data_layout": self._data_layout,
             "use_global_stats": self._use_global_stats},
            ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"])
        self._mean._assign_raw(outs[1]._val)
        self._variance._assign_raw(outs[2]._val)
        y = outs[0]
        if self._act:
            y = trace_op(self._act, {"X": [y]}, {}, ["Out"])[0]
        return y


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        self._epsilon = epsilon
        self._act = act
        self.weight = self.create_parameter(
            shape=[n], attr=param_attr, dtype=dtype,
            default_initializer=ConstantInitializer(1.0)) if scale else None
        self.bias = self.create_parameter(
            shape=[n], attr=bias_attr, dtype=dtype,
            is_bias=True) if shift else None

    def forward(self, input):
        ins = {"X": [input]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        outs = trace_op("layer_norm", ins,
                        {"begin_norm_axis": input.ndim - 1,
                         "epsilon": self._epsilon},
                        ["Y", "Mean", "Variance"])
        y = outs[0]
        if self._act:
            y = trace_op(self._act, {"X": [y]}, {}, ["Out"])[0]
        return y


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__()
        self._padding_idx = (-1 if padding_idx is None else
                             padding_idx if padding_idx >= 0
                             else size[0] + padding_idx)
        # is_sparse: backward yields a SelectedRows (rows, values) grad
        # instead of a dense vocab-sized scatter-add (reference:
        # lookup_table_op.h sparse path; core/selected_rows.py)
        self._is_sparse = bool(is_sparse)
        self.weight = self.create_parameter(
            shape=list(size), attr=param_attr, dtype=dtype)

    def forward(self, input):
        return trace_op("lookup_table_v2",
                        {"W": [self.weight], "Ids": [input]},
                        {"padding_idx": self._padding_idx,
                         "is_sparse": self._is_sparse}, ["Out"])[0]


class Dropout(Layer):
    def __init__(self, p=0.5, seed=None,
                 dropout_implementation="downgrade_in_infer",
                 is_test=False):
        super().__init__()
        self._p = p
        self._impl = dropout_implementation

    def forward(self, input):
        return trace_op("dropout", {"X": [input]},
                        {"dropout_prob": self._p,
                         "is_test": not self.training,
                         "dropout_implementation": self._impl},
                        ["Out", "Mask"])[0]


class GRUUnit(Layer):
    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__()
        self._size = size // 3
        d = self._size
        self.weight = self.create_parameter(shape=[d, d * 3],
                                            attr=param_attr, dtype=dtype)
        self.bias = self.create_parameter(shape=[1, d * 3], attr=bias_attr,
                                          dtype=dtype, is_bias=True)
        self._activation = activation
        self._gate_activation = gate_activation

    def forward(self, input, hidden):
        # gates = input + hidden @ weight + bias
        d = self._size
        hw = trace_op("matmul", {"X": [hidden], "Y": [self.weight]},
                      {"transpose_X": False, "transpose_Y": False,
                       "alpha": 1.0}, ["Out"])[0]
        g = input + hw
        if self.bias is not None:
            g = g + self.bias
        # split: update, reset, candidate
        parts = trace_op("split", {"X": [g]},
                         {"num": 3, "sections": [], "axis": 1},
                         {"Out": 3})
        u = trace_op(self._gate_activation, {"X": [parts[0]]}, {},
                     ["Out"])[0]
        r = trace_op(self._gate_activation, {"X": [parts[1]]}, {},
                     ["Out"])[0]
        c = trace_op(self._activation, {"X": [parts[2] * r]}, {}, ["Out"])[0]
        new_h = u * hidden + (base.wrap_raw(
            np.asarray(1.0, "float32")) - u) * c
        return new_h, new_h, g


class Conv2DTranspose(Layer):
    """Transposed conv (reference: dygraph/nn.py Conv2DTranspose over
    operators/conv_transpose_op.cc); lowers to lax.conv_transpose."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None,
                 dtype="float32"):
        super().__init__()
        filter_size = ([filter_size, filter_size]
                       if isinstance(filter_size, int)
                       else list(filter_size))
        self._stride = ([stride, stride] if isinstance(stride, int)
                        else list(stride))
        self._padding = ([padding, padding] if isinstance(padding, int)
                         else list(padding))
        self._dilation = ([dilation, dilation]
                          if isinstance(dilation, int) else list(dilation))
        self._groups = groups
        self._act = act
        fan_in = num_channels * filter_size[0] * filter_size[1]
        std = (2.0 / fan_in) ** 0.5
        # IOHW layout: (in_channels, out_channels/groups, kh, kw)
        self.weight = self.create_parameter(
            shape=[num_channels, num_filters // groups] + filter_size,
            attr=param_attr, dtype=dtype,
            default_initializer=NormalInitializer(0.0, std))
        self.bias = self.create_parameter(
            shape=[num_filters], attr=bias_attr, dtype=dtype, is_bias=True)

    def forward(self, input):
        out = trace_op("conv2d_transpose",
                       {"Input": [input], "Filter": [self.weight]},
                       {"strides": self._stride,
                        "paddings": self._padding,
                        "dilations": self._dilation,
                        "groups": self._groups},
                       ["Output"])[0]
        if self.bias is not None:
            out = trace_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]}, {"axis": 1},
                           ["Out"])[0]
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {}, ["Out"])[0]
        return out
