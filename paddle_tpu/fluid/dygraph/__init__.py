"""paddle_tpu.fluid.dygraph — imperative mode (reference:
`python/paddle/fluid/dygraph/`)."""
from . import base  # noqa: F401
from .base import (  # noqa: F401
    guard, no_grad, to_variable, enable_dygraph, disable_dygraph, Tracer,
    Tensor, VarBase, grad,
)
from .layers import Layer, Sequential, LayerList, ParameterList  # noqa: F401
from . import nn  # noqa: F401
from .nn import (  # noqa: F401
    Linear, Conv2D, Pool2D, BatchNorm, LayerNorm, Embedding, Dropout,
    GRUUnit,
)
from .parallel import (  # noqa: F401
    ParallelEnv, DataParallel, prepare_context, ParallelStrategy,
)
from .checkpoint import save_dygraph, load_dygraph  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    NoamDecay, PiecewiseDecay, NaturalExpDecay, ExponentialDecay,
    InverseTimeDecay, PolynomialDecay, CosineDecay, LinearLrWarmup,
    ReduceLROnPlateau,
)
from . import jit  # noqa: F401
from .jit import TracedLayer, declarative, to_static  # noqa: F401
from .dygraph_to_static import ProgramTranslator  # noqa: F401
