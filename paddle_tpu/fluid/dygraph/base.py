"""Dygraph (eager) engine: VarBase tensors + tape autograd.

Reference parity: `paddle/fluid/imperative/` — `Tracer::TraceOp`
(`tracer.cc:45-84`) runs ops eagerly through the same kernels and records
`OpBase` grad nodes; `BasicEngine::Execute` (`basic_engine.cc:159`) walks the
tape accumulating gradients. TPU-native redesign: every eager op dispatches
through a per-op jitted jax function (the analogue of the generated
`core.ops.*` fast path, `op_function_generator.cc:131-341`); the tape stores
(op, inputs, attrs) and `backward()` replays each node under `jax.vjp` —
i.e. gradients are recomputed functionally (rematerialisation) rather than
via hand-written grad kernels, which keeps eager memory low on HBM.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Dict, List, Optional

import numpy as np

from .. import framework
from ...core.rng import make_key as _mk_key
from ...core.types import normalize_dtype, to_numpy_dtype
from ...core.selected_rows import SelectedRows, sr_add
from ... import ops as ops_lib


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------

class Tracer:
    def __init__(self):
        self.tape: List["TapeEntry"] = []
        self._train_mode = True
        self._has_grad = True
        self._seed_counter = np.random.randint(0, 2**31 - 1)

    def next_rng_key(self):
        self._seed_counter += 1
        return _mk_key(self._seed_counter % (2**31 - 1))

    def record(self, entry):
        if self._has_grad:
            self.tape.append(entry)


class TapeEntry:
    __slots__ = ("op_type", "attrs", "in_slots", "in_tensors", "out_slots",
                 "out_tensors", "rng_key", "custom_vjp")

    def __init__(self, op_type, attrs, in_slots, in_tensors, out_slots,
                 out_tensors, rng_key, custom_vjp=None):
        self.op_type = op_type
        self.attrs = attrs
        self.in_slots = in_slots      # ((slot, count), ...) flat layout
        self.in_tensors = in_tensors  # flat list of Tensor
        self.out_slots = out_slots    # ((slot, count), ...) flat layout
        self.out_tensors = out_tensors  # flat list of Tensor
        self.rng_key = rng_key
        # custom_vjp(cotangents) -> flat grads aligned with in_tensors;
        # used by whole-subgraph entries (@declarative ConcreteProgram)
        self.custom_vjp = custom_vjp


def _tracer() -> Optional[Tracer]:
    return framework._dygraph_tracer()


# ---------------------------------------------------------------------------
# Tensor (VarBase)
# ---------------------------------------------------------------------------

class Tensor:
    """Eager tensor over a device-resident jax Array (VarBase,
    reference: imperative/layer.h + pybind/imperative.cc)."""

    def __init__(self, value, name=None, stop_gradient=False,
                 persistable=False, trainable=True):
        import jax.numpy as jnp

        if isinstance(value, Tensor):
            value = value._val
        elif isinstance(value, np.ndarray):
            value = jnp.asarray(value)
        self._val = value
        self.name = name or framework.unique_name("tensor")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = trainable
        self._grad = None
        self._backward_ran = False

    # -- data access -------------------------------------------------------
    def numpy(self):
        return np.asarray(self._val)

    def _value(self):
        return self._val

    def _assign_raw(self, arr):
        self._val = arr

    def _assign_value(self, other):
        self._val = other._val if isinstance(other, Tensor) else other

    @property
    def shape(self):
        return tuple(self._val.shape)

    @property
    def dtype(self):
        return normalize_dtype(self._val.dtype)

    @property
    def ndim(self):
        return self._val.ndim

    def __len__(self):
        return self._val.shape[0]

    def item(self):
        return np.asarray(self._val).reshape(-1)[0].item()

    def detach(self):
        return Tensor(self._val, stop_gradient=True)

    def clone(self):
        return trace_op("assign", {"X": [self]}, {}, ["Out"])[0]

    def astype(self, dtype):
        return trace_op("cast", {"X": [self]},
                        {"out_dtype": normalize_dtype(dtype)}, ["Out"])[0]

    # -- autograd ----------------------------------------------------------
    def backward(self, retain_graph=False):
        engine = BackwardEngine(_tracer())
        engine.run(self)
        self._backward_ran = True
        if not retain_graph:
            _tracer().tape.clear()

    def gradient(self):
        if self._grad is None:
            return None
        if isinstance(self._grad, SelectedRows):
            return np.asarray(self._grad.to_dense())
        return np.asarray(self._grad)

    def _grad_tensor(self):
        if self._grad is None:
            return None
        if isinstance(self._grad, SelectedRows):
            return self._grad  # duck-typed; optimizers take sparse path
        return Tensor(self._grad, stop_gradient=True)

    def clear_gradient(self):
        self._grad = None

    @property
    def grad(self):
        return self._grad_tensor()

    # -- operator sugar ----------------------------------------------------
    def _binary(self, other, op_type, reverse=False):
        import jax.numpy as jnp

        if np.isscalar(other):
            other = Tensor(jnp.asarray(
                np.asarray(other, to_numpy_dtype(self.dtype))),
                stop_gradient=True)
        a, b = (other, self) if reverse else (self, other)
        return trace_op(op_type, {"X": [a], "Y": [b]}, {"axis": -1},
                        ["Out"])[0]

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", reverse=True)

    def __mod__(self, o):
        return self._binary(o, "elementwise_mod")

    def __floordiv__(self, o):
        return self._binary(o, "elementwise_floordiv")

    def __pow__(self, o):
        return self._binary(o, "elementwise_pow")

    def __neg__(self):
        return trace_op("scale", {"X": [self]},
                        {"scale": -1.0, "bias": 0.0,
                         "bias_after_scale": True}, ["Out"])[0]

    def __matmul__(self, o):
        return trace_op("matmul", {"X": [self], "Y": [o]},
                        {"transpose_X": False, "transpose_Y": False,
                         "alpha": 1.0}, ["Out"])[0]

    def _compare(self, other, op_type):
        import jax.numpy as jnp

        if not isinstance(other, Tensor):
            other = Tensor(jnp.asarray(
                np.asarray(other, to_numpy_dtype(self.dtype))),
                stop_gradient=True)
        return trace_op(op_type, {"X": [self], "Y": [other]}, {},
                        ["Out"])[0]

    def __lt__(self, o):
        return self._compare(o, "less_than")

    def __le__(self, o):
        return self._compare(o, "less_equal")

    def __gt__(self, o):
        return self._compare(o, "greater_than")

    def __ge__(self, o):
        return self._compare(o, "greater_equal")

    def __eq__(self, o):
        if o is None:
            return False
        return self._compare(o, "equal")

    def __ne__(self, o):
        if o is None:
            return True
        return self._compare(o, "not_equal")

    # identity hash (elementwise __eq__ would otherwise make Tensors
    # unhashable; matches VarBase semantics)
    __hash__ = object.__hash__

    def __getitem__(self, idx):
        out = self._val[idx]
        t = Tensor(out, stop_gradient=self.stop_gradient)
        return t

    def reshape(self, shape):
        return trace_op("reshape2", {"X": [self]},
                        {"shape": [int(s) for s in shape]},
                        ["Out", "XShape"])[0]

    def __repr__(self):
        return "Tensor(shape=%s, dtype=%s, stop_gradient=%s)\n%r" % (
            self.shape, self.dtype, self.stop_gradient, np.asarray(self._val))


VarBase = Tensor


# ---------------------------------------------------------------------------
# eager op dispatch
# ---------------------------------------------------------------------------

def raw_op(op_type, ins_raw: Dict[str, list], attrs, out_slots,
           rng_key=None):
    """Run one op on raw arrays (no tape). Returns flat outputs in
    out_slots order."""
    outs = ops_lib.eager_run(op_type, ins_raw, attrs, rng_key=rng_key)
    flat = []
    for slot in out_slots:
        flat.extend(outs.get(slot, []))
    return flat


def wrap_raw(arr):
    # an Executor LazyFetch handle stays device-resident: unwrap the
    # raw jax Array rather than forcing a host materialization here
    from ..executor import LazyFetch

    if isinstance(arr, LazyFetch):
        arr = arr.value
    return Tensor(arr, stop_gradient=True)


def to_tensor_value(arr):
    import jax.numpy as jnp

    return jnp.asarray(arr)


def trace_op(op_type, ins: Dict[str, list], attrs, out_slots):
    """Eager execution + tape recording. `ins` maps slot -> [Tensor...].
    Under @declarative capture this choke point redirects to the static
    front end instead (the TPU-native ProgramDescTracer, see
    dygraph_to_static/)."""
    tracer = _tracer()
    if tracer is None:
        from .dygraph_to_static import program_translator as _pt

        if _pt.current_ctx() is not None:
            return _pt.capture_trace_op(op_type, ins, attrs, out_slots)
        raise RuntimeError("trace_op called outside dygraph mode")
    opdef = ops_lib.get_op(op_type)
    attrs = {k: v for k, v in attrs.items() if v is not None}
    if not tracer._train_mode and "is_test" in attrs:
        attrs["is_test"] = True
    ins_clean = {slot: [t for t in ts if t is not None]
                 for slot, ts in ins.items()}
    ins_clean = {s: ts for s, ts in ins_clean.items() if ts}
    raw_ins = {slot: [t._val for t in ts] for slot, ts in ins_clean.items()}
    rng_key = tracer.next_rng_key() if opdef.needs_rng else None
    outs = ops_lib.eager_run(op_type, raw_ins, attrs, rng_key=rng_key)

    requires_grad = tracer._has_grad and any(
        not t.stop_gradient for ts in ins_clean.values() for t in ts)
    flat_out = []
    slot_counts = []
    for slot in (out_slots if not isinstance(out_slots, dict)
                 else out_slots):
        vals = outs.get(slot, [])
        slot_counts.append((slot, len(vals)))
        for v in vals:
            flat_out.append(Tensor(v, stop_gradient=not requires_grad))

    if requires_grad:
        in_layout = tuple((slot, len(ts))
                          for slot, ts in sorted(ins_clean.items()))
        in_flat = [t for _, ts in sorted(ins_clean.items()) for t in ts]
        custom_vjp = None
        if attrs.get("is_sparse") and op_type in ("lookup_table",
                                                  "lookup_table_v2"):
            custom_vjp = _sparse_lookup_vjp(ins_clean, in_flat, attrs)
        tracer.record(TapeEntry(op_type, dict(attrs), in_layout, in_flat,
                                tuple(slot_counts), flat_out, rng_key,
                                custom_vjp=custom_vjp))
    return flat_out


def _sparse_lookup_vjp(ins_clean, in_flat, attrs):
    """is_sparse embedding backward: the weight grad is a SelectedRows
    (rows=ids, values=output cotangent rows) instead of a dense
    vocab-sized scatter-add (reference: lookup_table_grad sparse path,
    `operators/lookup_table_op.h` + `framework/selected_rows.h`)."""
    ids_t = ins_clean["Ids"][0]
    w_t = ins_clean["W"][0]
    padding_idx = attrs.get("padding_idx", -1)

    def vjp(cotangents):
        import jax.numpy as jnp

        from ...core.selected_rows import SelectedRows

        ct = cotangents[0]
        dim = w_t._val.shape[-1]
        rows = jnp.reshape(ids_t._val, (-1,)).astype(jnp.int64)
        values = jnp.reshape(ct, (-1, dim)).astype(w_t._val.dtype)
        if padding_idx is not None and padding_idx >= 0:
            mask = rows != padding_idx
            values = jnp.where(mask[:, None], values, 0)
        sr = SelectedRows(rows, values, w_t._val.shape[0])
        grads = []
        for t in in_flat:
            grads.append(sr if t is w_t else None)
        return grads

    return vjp


# ---------------------------------------------------------------------------
# backward engine (reference: imperative/basic_engine.cc:159)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=4096)
def _vjp_fn(op_type, attr_items, in_layout, in_shapes, out_layout, has_rng):
    """Cached jitted vjp for one op instance shape-signature."""
    import jax

    opdef = ops_lib.get_op(op_type)
    attrs = dict(attr_items)

    def fwd(flat_args, key):
        ins, i = {}, 0
        for slot, n in in_layout:
            ins[slot] = list(flat_args[i:i + n])
            i += n
        a = dict(attrs)
        if has_rng:
            a["_rng_key"] = key
        outs = ops_lib.normalize_outs(opdef.compute(ins, a))
        flat = []
        for slot, n in out_layout:
            flat.extend(outs.get(slot, []))
        return flat

    def run(flat_args, key, cotangents):
        primals, f_vjp = jax.vjp(lambda fa: fwd(fa, key), list(flat_args))
        grads = f_vjp(list(cotangents))[0]
        return grads

    return jax.jit(run)


class BackwardEngine:
    def __init__(self, tracer: Tracer):
        self.tracer = tracer

    def run(self, loss: Tensor):
        import jax
        import jax.numpy as jnp

        grads: Dict[int, object] = {id(loss): jnp.ones_like(loss._val)}
        tensors: Dict[int, Tensor] = {id(loss): loss}

        for entry in reversed(self.tracer.tape):
            needs = any(id(t) in grads for t in entry.out_tensors)
            if not needs:
                continue
            cotangents = []
            for t in entry.out_tensors:
                g = grads.get(id(t))
                if g is None or not jnp.issubdtype(t._val.dtype,
                                                   jnp.inexact):
                    g = jnp.zeros_like(t._val)
                cotangents.append(g)
            if entry.custom_vjp is not None:
                in_grads = entry.custom_vjp(cotangents)
            else:
                attr_items = tuple(sorted(
                    (k, ops_lib.registry._hashable_attr(v))
                    for k, v in entry.attrs.items() if not k.startswith("_")))
                in_shapes = tuple((t._val.shape, str(t._val.dtype))
                                  for t in entry.in_tensors)
                fn = _vjp_fn(entry.op_type, attr_items, entry.in_slots,
                             in_shapes, entry.out_slots,
                             entry.rng_key is not None)
                key = entry.rng_key if entry.rng_key is not None else \
                    _mk_key(0)
                in_grads = fn([t._val for t in entry.in_tensors], key,
                              cotangents)
            for t, g in zip(entry.in_tensors, in_grads):
                if t.stop_gradient or g is None:
                    continue
                if not jnp.issubdtype(t._val.dtype, jnp.inexact):
                    continue
                if hasattr(g, "dtype") and str(g.dtype) == "float0":
                    continue
                acc = grads.get(id(t))
                grads[id(t)] = g if acc is None else sr_add(acc, g)
                tensors[id(t)] = t

        # publish: accumulate into persistent .grad (reference:
        # GradientAccumulator semantics — grads sum across backward calls
        # until clear_gradient; SelectedRows grads concatenate rows,
        # imperative/gradient_accumulator.cc sparse branch)
        for tid, g in grads.items():
            t = tensors.get(tid)
            if t is None:
                continue
            t._grad = g if t._grad is None else sr_add(t._grad, g)


# ---------------------------------------------------------------------------
# mode management (reference: fluid/dygraph/base.py guard/enable_dygraph)
# ---------------------------------------------------------------------------

_global_tracer = None


def enable_dygraph(place=None):
    global _global_tracer
    if _global_tracer is None:
        _global_tracer = Tracer()
    framework._switch_tracer(_global_tracer)


def disable_dygraph():
    framework._switch_tracer(None)


@contextlib.contextmanager
def guard(place=None):
    global _global_tracer
    tracer = Tracer()
    old_global = _global_tracer
    _global_tracer = tracer
    old = framework._switch_tracer(tracer)
    try:
        yield
    finally:
        framework._switch_tracer(old)
        _global_tracer = old_global


class no_grad:
    """Context manager AND decorator disabling tape recording
    (reference: dygraph/base.py no_grad)."""

    def __init__(self, func=None):
        self._func = func

    def __call__(self, *args, **kwargs):
        if self._func is not None:
            with no_grad():
                return self._func(*args, **kwargs)
        raise TypeError("no_grad used incorrectly")

    def __enter__(self):
        t = _tracer()
        self._saved = t._has_grad if t else None
        if t:
            t._has_grad = False
        return self

    def __exit__(self, *a):
        t = _tracer()
        if t and self._saved is not None:
            t._has_grad = self._saved


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, Tensor):
        return value
    from ...reader.prefetcher import is_on_device

    if is_on_device(value):
        # already a device array (e.g. from reader.prefetch_to_device):
        # wrap without the host round-trip np.asarray would force
        return Tensor(value, name=name, stop_gradient=True)
    return Tensor(np.asarray(value), name=name,
                  stop_gradient=True)


class _FakeInitBlock:
    """Captures a single initializer op and runs it eagerly."""

    def __init__(self):
        self.result = None

    def append_op(self, type=None, inputs=None, outputs=None, attrs=None):
        outs = ops_lib.eager_run(
            type, {}, attrs or {},
            rng_key=(_tracer() or Tracer()).next_rng_key()
            if ops_lib.get_op(type).needs_rng else None)
        self.result = outs["Out"][0]


def create_eager_parameter(attr, shape, dtype, initializer, trainable=True,
                           name=None):
    """Eager analogue of LayerHelper.create_parameter."""
    from ..framework import Variable

    class _V:
        pass

    v = _V()
    v.shape = tuple(shape)
    v.dtype = normalize_dtype(dtype)
    blk = _FakeInitBlock()
    initializer(v, blk)
    pname = name
    if pname is None and attr is not None and getattr(attr, "name", None):
        pname = attr.name
    t = Tensor(blk.result, name=pname or framework.unique_name("param"),
               stop_gradient=not trainable, persistable=True,
               trainable=trainable)
    if attr is not None:
        t.optimize_attr = {"learning_rate": getattr(attr, "learning_rate",
                                                    1.0)}
        t.regularizer = getattr(attr, "regularizer", None)
    else:
        t.optimize_attr = {"learning_rate": 1.0}
        t.regularizer = None
    return t


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad partial-gradient API (reference:
    imperative/partial_grad_engine.cc)."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    saved = {id(t): t._grad for t in inputs}
    for t in inputs:
        t._grad = None
    engine = BackwardEngine(_tracer())
    engine.run(outputs[0])
    result = []
    for t in inputs:
        g = t._grad
        if g is None and not allow_unused:
            import jax.numpy as jnp

            g = jnp.zeros_like(t._val)
        result.append(Tensor(g, stop_gradient=True) if g is not None
                      else None)
        t._grad = saved[id(t)]
    if not retain_graph:
        _tracer().tape.clear()
    return result
