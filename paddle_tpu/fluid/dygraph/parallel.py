"""Dygraph data parallelism (reference:
`python/paddle/fluid/dygraph/parallel.py:56-369` — ParallelEnv env
contract, DataParallel with loss scaling + coalesced `_c_allreduce`).

TPU-native: eager tensors are global jax Arrays; when a mesh is active the
batch axis is sharded and XLA inserts the gradient all-reduce during the
backward computation, so `scale_loss`/`apply_collective_grads` keep their
API but the collective itself rides ICI via psum (see
paddle_tpu/ops/collective_ops.py). Multi-host bootstrap goes through
`paddle_tpu.distributed.init_parallel_env` (jax.distributed over DCN,
replacing the NCCL-id TCP exchange `imperative/nccl_context.cc:21-63`).
"""
from __future__ import annotations

import os

import numpy as np

from . import base
from .layers import Layer
from ...parallel import env as penv


class ParallelEnv:
    """Env-var driven rank info (reference: parallel.py:56)."""

    def __init__(self):
        self._rank = penv.trainer_id()
        self._world_size = penv.trainer_num()

    @property
    def rank(self):
        return self._rank

    @property
    def local_rank(self):
        return self._rank

    @property
    def nranks(self):
        return self._world_size

    @property
    def world_size(self):
        return self._world_size

    @property
    def dev_id(self):
        return int(os.environ.get("FLAGS_selected_gpus", "0").split(",")[0])

    @property
    def current_endpoint(self):
        return penv.current_endpoint()

    @property
    def trainer_endpoints(self):
        return penv.trainer_endpoints()


Env = ParallelEnv


def prepare_context(strategy=None):
    return ParallelEnv()


class ParallelStrategy:
    def __init__(self):
        self.nranks = 1
        self.local_rank = 0
        self.trainer_endpoints = []
        self.current_endpoint = ""


class DataParallel(Layer):
    """Wraps a Layer for data-parallel training (reference:
    parallel.py:225). With a live mesh, gradients of replicated params are
    reduced by XLA automatically; these methods keep the fluid contract."""

    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy or ParallelStrategy()
        self._nranks = max(ParallelEnv().nranks, 1)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        if self._nranks <= 1:
            return loss
        return loss * (1.0 / self._nranks)

    def apply_collective_grads(self):
        # grads on global arrays are already reduced by XLA when the batch
        # axis is sharded; explicit coalesce+allreduce (parallel.py:344-369)
        # is unnecessary on a single host. Multi-host: psum via mesh.
        # With FLAGS_tpu_sharded_weight_update, this is where the eager
        # path re-lays gradients out dim-0-sharded over the mesh (the
        # ZeRO-1 reduce-scatter analogue): the optimizer step that
        # follows then runs GSPMD-partitioned against the equally
        # sharded accumulators — per-replica update FLOPs and moment
        # HBM ~1/N, math unchanged (XLA all-gathers the params where
        # the next replicated forward consumes them).
        mesh = penv.global_mesh()
        if mesh is None:
            return
        import jax

        from ...core.selected_rows import SelectedRows
        from ...parallel.sharded_update import eager_accumulator_sharding

        for p in self._layers.parameters():
            g = p._grad
            if g is None or isinstance(g, SelectedRows):
                continue
            sh = eager_accumulator_sharding(tuple(g.shape))
            if sh is not None and getattr(g, "sharding", None) != sh:
                p._grad = jax.device_put(g, sh)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    load_dict = set_dict
