"""AST conversion of python control flow onto the convert_* runtime
helpers (reference: the ~20 transformer files in
`dygraph_to_static/` — IfElseTransformer, LoopTransformer,
LogicalTransformer). Scope kept to the constructs that matter for
model code:

- `if` / `elif` / `else`  -> convert_ifelse (lax.cond when the test is
  a tensor). Branches either assign variables (rewritten to an output
  tuple) or are both single `return` statements.
- `while`                 -> convert_while_loop (lax.while_loop when
  the test is a tensor); loop-carried vars = names assigned in the body.
- `and` / `or` / `not`    -> convert_logical_* (short-circuit preserved
  for python values via thunks).
- `for i in range(...)` and python-value `if`/`while` keep plain python
  semantics (they unroll / run at capture time, exactly like jax.jit).

Unsupported in converted code: `break`/`continue` inside a tensor
`while`, early `return` from inside a tensor `if` branch that also
assigns — these raise with a clear message at conversion time.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap


_JST = "_paddle_jst"


class _AssignedNames(ast.NodeVisitor):
    """Names bound by statements in a body (assign/augassign/for/with)."""

    def __init__(self):
        self.names = []

    def _add(self, node):
        if isinstance(node, ast.Name):
            if node.id not in self.names:
                self.names.append(node.id)
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                self._add(e)

    def visit_Assign(self, node):
        for t in node.targets:
            self._add(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if node.name not in self.names:
            self.names.append(node.name)
        # don't descend: inner defs have their own scope


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _HasCtl(ast.NodeVisitor):
    def __init__(self):
        self.found = False

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True

    def visit_Return(self, node):
        self.found = True

    def visit_While(self, node):
        pass  # nested loops own their break/continue

    def visit_For(self, node):
        pass

    def visit_FunctionDef(self, node):
        pass


def _has_ctl(stmts):
    v = _HasCtl()
    for s in stmts:
        v.visit(s)
    return v.found


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _assign_const(name, value):
    return ast.Assign(targets=[_name(name, ast.Store())],
                      value=ast.Constant(value=value))


def _ends_with_return(stmts):
    return bool(stmts) and isinstance(stmts[-1], ast.Return)


class _HasLoopCtl(ast.NodeVisitor):
    """break/continue at this loop's level (nested loops own theirs)."""

    def __init__(self):
        self.found = False

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True

    def visit_While(self, node):
        pass

    def visit_For(self, node):
        pass

    def visit_FunctionDef(self, node):
        pass


def _has_loop_ctl(stmts):
    v = _HasLoopCtl()
    for s in stmts:
        v.visit(s)
    return v.found


class FlowNormalizer(ast.NodeTransformer):
    """Pre-pass desugaring return-flow and loop break/continue into the
    assign-and-branch shapes the main transformer lowers (reference:
    return_transformer.py + break_continue_transformer.py, via flag
    variables; here break/continue become guard flags and early returns
    fold the remaining statements into the else branch — continuation
    style — so tensor conditions reach lax.cond/while_loop instead of
    raising python_only)."""

    def __init__(self):
        self._n = 0

    def _fresh(self, base):
        self._n += 1
        return "__%s_%d" % (base, self._n)

    # -- return-flow: fold statements after a returning `if` into its
    # else branch, so `if c: return a` + rest becomes a both-return if
    def _fold_returns(self, stmts, at_function_tail):
        out = list(stmts)
        for i, s in enumerate(out):
            if not isinstance(s, ast.If):
                continue
            body_ret = _ends_with_return(s.body)
            else_ret = _ends_with_return(s.orelse)
            if not (body_ret or else_ret):
                continue
            rest = out[i + 1:]
            # build the folded branch FIRST and only commit it to the
            # node once the fold is certain: mutating s.orelse/s.body
            # before a `break` would leave `rest` both inside the branch
            # and in the returned tail — executing it twice (ADVICE r3)
            if body_ret and not else_ret:
                folded = (s.orelse or []) + rest
                if not _ends_with_return(folded):
                    if not at_function_tail:
                        break  # can't prove the tail returns; leave it
                    folded = folded + [
                        ast.Return(value=ast.Constant(value=None))]
                s.orelse = folded
            elif else_ret and not body_ret:
                folded = s.body + rest
                if not _ends_with_return(folded):
                    if not at_function_tail:
                        break
                    folded = folded + [
                        ast.Return(value=ast.Constant(value=None))]
                s.body = folded
            elif rest:
                break  # both branches return: rest is dead; leave as-is
            s.body = self._fold_returns(s.body, at_function_tail)
            s.orelse = self._fold_returns(s.orelse, at_function_tail)
            return out[:i] + [s]
        return out

    def visit_FunctionDef(self, node):
        self.generic_visit(node)
        node.body = self._fold_returns(node.body, at_function_tail=True)
        return node

    # -- break/continue: guard-flag rewrite around the while body
    def _rewrite_ctl(self, stmts, brk, cnt):
        out = []
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Break):
                out.append(_assign_const(brk, True))
                return out  # rest is unreachable
            if isinstance(s, ast.Continue):
                out.append(_assign_const(cnt, True))
                return out
            if isinstance(s, ast.If) and (_has_loop_ctl(s.body)
                                          or _has_loop_ctl(s.orelse)):
                s.body = self._rewrite_ctl(s.body, brk, cnt)
                s.orelse = self._rewrite_ctl(s.orelse, brk, cnt)
                out.append(s)
                rest = self._rewrite_ctl(stmts[i + 1:], brk, cnt)
                if rest:
                    guard = ast.UnaryOp(
                        op=ast.Not(),
                        operand=ast.BoolOp(op=ast.Or(),
                                           values=[_name(brk),
                                                   _name(cnt)]))
                    out.append(ast.If(test=guard, body=rest, orelse=[]))
                return out
            out.append(s)
        return out

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or not _has_loop_ctl(node.body):
            return node
        brk, cnt = self._fresh("brk"), self._fresh("cnt")
        body = [_assign_const(cnt, False)] + self._rewrite_ctl(
            list(node.body), brk, cnt)
        test = ast.BoolOp(
            op=ast.And(),
            values=[ast.UnaryOp(op=ast.Not(), operand=_name(brk)),
                    node.test])
        new_loop = ast.While(test=test, body=body, orelse=[])
        return [_assign_const(brk, False), _assign_const(cnt, False),
                new_loop]


def _jst_call(fn_name, args):
    return ast.Call(
        func=ast.Attribute(value=_name(_JST), attr=fn_name,
                           ctx=ast.Load()),
        args=args, keywords=[])


def _out_tuple(names, ctx):
    return ast.Tuple(elts=[_name(n, ctx) for n in names], ctx=ctx)


_GEN_PREFIX = "__d2s_"


def _carryable(names):
    """Drop transformer-generated helper names (branch/cond function
    defs) — they are bound and called within one statement and must
    never become if-merge outputs or loop-carried values."""
    return [n for n in names if not n.startswith(_GEN_PREFIX)]


class DygraphToStaticTransformer(ast.NodeTransformer):
    def __init__(self):
        self._counter = 0

    def _fresh(self, base):
        self._counter += 1
        return "%s%s_%d" % (_GEN_PREFIX, base, self._counter)

    # -- boolean operators --------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = "convert_logical_and" if isinstance(node.op, ast.And) \
            else "convert_logical_or"
        expr = node.values[-1]
        for v in reversed(node.values[:-1]):
            expr = _jst_call(fn, [
                ast.Lambda(args=_no_args(), body=v),
                ast.Lambda(args=_no_args(), body=expr)])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _jst_call("convert_logical_not", [node.operand])
        return node

    # -- if ------------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body, orelse = node.body, node.orelse
        if (body and isinstance(body[-1], ast.Return)
                and orelse and isinstance(orelse[-1], ast.Return)):
            # both branches END with return (FlowNormalizer folds early
            # returns into this shape): continuation-style conversion —
            # the whole if IS the function's return. Names a branch
            # assigns become PARAMETERS (same reason as the merge path
            # below: an assignment makes the name branch-local, so a
            # read of the incoming value would raise UnboundLocalError)
            names = sorted(set(_carryable(_assigned(body)))
                           | set(_carryable(_assigned(orelse))))
            args = ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=n) for n in names],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[])
            t_name, f_name = self._fresh("ret_t"), self._fresh("ret_f")
            t_def = ast.FunctionDef(name=t_name, args=args,
                                    body=body, decorator_list=[],
                                    returns=None)
            f_def = ast.FunctionDef(name=f_name, args=args,
                                    body=orelse, decorator_list=[],
                                    returns=None)
            init = ast.Tuple(
                elts=[_jst_call("try_get", [
                    ast.Lambda(args=_no_args(), body=_name(n))])
                    for n in names],
                ctx=ast.Load())
            ret = ast.Return(value=_jst_call("convert_ifelse", [
                node.test, _name(t_name), _name(f_name), init]))
            return [t_def, f_def, ret]
        if _has_ctl(body) or _has_ctl(orelse):
            # guard clauses (`if flag: return x`) keep python semantics;
            # python_only raises at capture time if the test is a tensor
            node.test = _jst_call("python_only", [
                node.test,
                ast.Constant(value="if-with-return/break/continue")])
            return node
        names = sorted(set(_carryable(_assigned(body)))
                       | set(_carryable(_assigned(orelse))))
        t_name, f_name = self._fresh("true_fn"), self._fresh("false_fn")
        # branch functions take the pre-branch values as PARAMETERS —
        # python scoping would otherwise treat every assigned name as a
        # fresh local and break reads of the incoming value
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        ret = ast.Return(value=_out_tuple(names, ast.Load()))
        t_def = ast.FunctionDef(
            name=t_name, args=args,
            body=(body + [ret]) if names else (body + [_pass()]),
            decorator_list=[], returns=None)
        f_def = ast.FunctionDef(
            name=f_name, args=args,
            body=(orelse + [ret]) if names
            else ((orelse or [_pass()]) + []),
            decorator_list=[], returns=None)
        init = ast.Tuple(
            elts=[_jst_call("try_get", [
                ast.Lambda(args=_no_args(), body=_name(n))])
                for n in names],
            ctx=ast.Load())
        call = _jst_call("convert_ifelse", [
            node.test, _name(t_name), _name(f_name), init])
        if names:
            assign = ast.Assign(
                targets=[_out_tuple(names, ast.Store())], value=call)
        else:
            assign = ast.Expr(value=call)
        return [t_def, f_def, assign]

    # -- builtin calls: print / int / float / bool / len --------------------
    def visit_Call(self, node):
        self.generic_visit(node)
        if isinstance(node.func, ast.Name) and not node.keywords:
            fid = node.func.id
            if fid == "print":
                return _jst_call("convert_print", node.args)
            if fid in ("int", "float", "bool") and len(node.args) == 1:
                return _jst_call("convert_cast",
                                 [node.args[0], ast.Constant(value=fid)])
            if fid == "len" and len(node.args) == 1:
                return _jst_call("convert_len", node.args)
        return node

    # -- assert --------------------------------------------------------------
    def visit_Assert(self, node):
        self.generic_visit(node)
        args = [node.test] + ([node.msg] if node.msg is not None else [])
        return ast.Expr(value=_jst_call("convert_assert", args))

    # -- while ---------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_ctl(node.body):
            # python-valued loops with break/continue/else keep python
            # semantics; tensor tests in that shape are rejected at
            # capture time by python_only
            node.test = _jst_call("python_only", [
                node.test,
                ast.Constant(value="while-with-break/continue/else")])
            return node
        names = sorted(set(_carryable(_assigned(node.body))))
        if not names:
            raise NotImplementedError(
                "@declarative: `while` body assigns no variables")
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        c_name, b_name = self._fresh("cond_fn"), self._fresh("body_fn")
        c_def = ast.FunctionDef(
            name=c_name, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[],
            returns=None)
        b_def = ast.FunctionDef(
            name=b_name, args=args,
            body=node.body + [ast.Return(
                value=_out_tuple(names, ast.Load()))],
            decorator_list=[], returns=None)
        init = ast.Tuple(
            elts=[_jst_call("try_get", [
                ast.Lambda(args=_no_args(), body=_name(n))])
                for n in names],
            ctx=ast.Load())
        call = _jst_call("convert_while_loop", [
            _name(c_name), _name(b_name), init])
        assign = ast.Assign(targets=[_out_tuple(names, ast.Store())],
                            value=call)
        return [c_def, b_def, assign]


def _no_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _pass():
    return ast.Pass()


@functools.lru_cache(maxsize=512)
def _convert_cached(fn):
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn  # no source (builtins, lambdas in REPL) — run as-is
    tree = ast.parse(src)
    fd = tree.body[0]
    fd.decorator_list = []
    tree = FlowNormalizer().visit(tree)
    tree = DygraphToStaticTransformer().visit(tree)
    ast.fix_missing_locations(tree)
    code = compile(tree, filename="<declarative:%s>" % fn.__qualname__,
                   mode="exec")
    from . import convert_operators

    glb = dict(fn.__globals__)
    glb[_JST] = convert_operators
    if fn.__closure__:
        # rebind free variables by wrapping in a maker function
        free = fn.__code__.co_freevars
        cells = {n: c.cell_contents for n, c in
                 zip(free, fn.__closure__)}
        glb.update(cells)
    exec(code, glb)
    new_fn = glb[fd.name]
    functools.update_wrapper(new_fn, fn)
    return new_fn


def convert_to_static(fn):
    """Return the AST-converted twin of `fn` (cached per function)."""
    return _convert_cached(fn)
