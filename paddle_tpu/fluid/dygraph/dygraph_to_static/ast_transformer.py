"""AST conversion of python control flow onto the convert_* runtime
helpers (reference: the ~20 transformer files in
`dygraph_to_static/` — IfElseTransformer, LoopTransformer,
LogicalTransformer). Scope kept to the constructs that matter for
model code:

- `if` / `elif` / `else`  -> convert_ifelse (lax.cond when the test is
  a tensor). Branches either assign variables (rewritten to an output
  tuple) or are both single `return` statements.
- `while`                 -> convert_while_loop (lax.while_loop when
  the test is a tensor); loop-carried vars = names assigned in the body.
- `and` / `or` / `not`    -> convert_logical_* (short-circuit preserved
  for python values via thunks).
- `for i in range(...)` and python-value `if`/`while` keep plain python
  semantics (they unroll / run at capture time, exactly like jax.jit).

Unsupported in converted code: `break`/`continue` inside a tensor
`while`, early `return` from inside a tensor `if` branch that also
assigns — these raise with a clear message at conversion time.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap


_JST = "_paddle_jst"


class _AssignedNames(ast.NodeVisitor):
    """Names bound by statements in a body (assign/augassign/for/with)."""

    def __init__(self):
        self.names = []

    def _add(self, node):
        if isinstance(node, ast.Name):
            if node.id not in self.names:
                self.names.append(node.id)
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                self._add(e)

    def visit_Assign(self, node):
        for t in node.targets:
            self._add(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if node.name not in self.names:
            self.names.append(node.name)
        # don't descend: inner defs have their own scope


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _HasCtl(ast.NodeVisitor):
    def __init__(self):
        self.found = False

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True

    def visit_Return(self, node):
        self.found = True

    def visit_While(self, node):
        pass  # nested loops own their break/continue

    def visit_For(self, node):
        pass

    def visit_FunctionDef(self, node):
        pass


def _has_ctl(stmts):
    v = _HasCtl()
    for s in stmts:
        v.visit(s)
    return v.found


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_call(fn_name, args):
    return ast.Call(
        func=ast.Attribute(value=_name(_JST), attr=fn_name,
                           ctx=ast.Load()),
        args=args, keywords=[])


def _out_tuple(names, ctx):
    return ast.Tuple(elts=[_name(n, ctx) for n in names], ctx=ctx)


class DygraphToStaticTransformer(ast.NodeTransformer):
    def __init__(self):
        self._counter = 0

    def _fresh(self, base):
        self._counter += 1
        return "__%s_%d" % (base, self._counter)

    # -- boolean operators --------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = "convert_logical_and" if isinstance(node.op, ast.And) \
            else "convert_logical_or"
        expr = node.values[-1]
        for v in reversed(node.values[:-1]):
            expr = _jst_call(fn, [
                ast.Lambda(args=_no_args(), body=v),
                ast.Lambda(args=_no_args(), body=expr)])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _jst_call("convert_logical_not", [node.operand])
        return node

    # -- if ------------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body, orelse = node.body, node.orelse
        both_return = (
            len(body) == 1 and isinstance(body[0], ast.Return) and
            len(orelse) == 1 and isinstance(orelse[0], ast.Return))
        if both_return:
            return ast.Return(value=_jst_call("convert_ifelse", [
                node.test,
                ast.Lambda(args=_no_args(), body=body[0].value),
                ast.Lambda(args=_no_args(), body=orelse[0].value)]))
        if _has_ctl(body) or _has_ctl(orelse):
            # guard clauses (`if flag: return x`) keep python semantics;
            # python_only raises at capture time if the test is a tensor
            node.test = _jst_call("python_only", [
                node.test,
                ast.Constant(value="if-with-return/break/continue")])
            return node
        names = sorted(set(_assigned(body)) | set(_assigned(orelse)))
        t_name, f_name = self._fresh("true_fn"), self._fresh("false_fn")
        # branch functions take the pre-branch values as PARAMETERS —
        # python scoping would otherwise treat every assigned name as a
        # fresh local and break reads of the incoming value
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        ret = ast.Return(value=_out_tuple(names, ast.Load()))
        t_def = ast.FunctionDef(
            name=t_name, args=args,
            body=(body + [ret]) if names else (body + [_pass()]),
            decorator_list=[], returns=None)
        f_def = ast.FunctionDef(
            name=f_name, args=args,
            body=(orelse + [ret]) if names
            else ((orelse or [_pass()]) + []),
            decorator_list=[], returns=None)
        init = ast.Tuple(
            elts=[_jst_call("try_get", [
                ast.Lambda(args=_no_args(), body=_name(n))])
                for n in names],
            ctx=ast.Load())
        call = _jst_call("convert_ifelse", [
            node.test, _name(t_name), _name(f_name), init])
        if names:
            assign = ast.Assign(
                targets=[_out_tuple(names, ast.Store())], value=call)
        else:
            assign = ast.Expr(value=call)
        return [t_def, f_def, assign]

    # -- while ---------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_ctl(node.body):
            # python-valued loops with break/continue/else keep python
            # semantics; tensor tests in that shape are rejected at
            # capture time by python_only
            node.test = _jst_call("python_only", [
                node.test,
                ast.Constant(value="while-with-break/continue/else")])
            return node
        names = sorted(set(_assigned(node.body)))
        if not names:
            raise NotImplementedError(
                "@declarative: `while` body assigns no variables")
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        c_name, b_name = self._fresh("cond_fn"), self._fresh("body_fn")
        c_def = ast.FunctionDef(
            name=c_name, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[],
            returns=None)
        b_def = ast.FunctionDef(
            name=b_name, args=args,
            body=node.body + [ast.Return(
                value=_out_tuple(names, ast.Load()))],
            decorator_list=[], returns=None)
        init = ast.Tuple(
            elts=[_jst_call("try_get", [
                ast.Lambda(args=_no_args(), body=_name(n))])
                for n in names],
            ctx=ast.Load())
        call = _jst_call("convert_while_loop", [
            _name(c_name), _name(b_name), init])
        assign = ast.Assign(targets=[_out_tuple(names, ast.Store())],
                            value=call)
        return [c_def, b_def, assign]


def _no_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _pass():
    return ast.Pass()


@functools.lru_cache(maxsize=512)
def _convert_cached(fn):
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn  # no source (builtins, lambdas in REPL) — run as-is
    tree = ast.parse(src)
    fd = tree.body[0]
    fd.decorator_list = []
    tree = DygraphToStaticTransformer().visit(tree)
    ast.fix_missing_locations(tree)
    code = compile(tree, filename="<declarative:%s>" % fn.__qualname__,
                   mode="exec")
    from . import convert_operators

    glb = dict(fn.__globals__)
    glb[_JST] = convert_operators
    if fn.__closure__:
        # rebind free variables by wrapping in a maker function
        free = fn.__code__.co_freevars
        cells = {n: c.cell_contents for n, c in
                 zip(free, fn.__closure__)}
        glb.update(cells)
    exec(code, glb)
    new_fn = glb[fd.name]
    functools.update_wrapper(new_fn, fn)
    return new_fn


def convert_to_static(fn):
    """Return the AST-converted twin of `fn` (cached per function)."""
    return _convert_cached(fn)
