"""Runtime conversion helpers the AST transformer targets (reference:
`dygraph_to_static/convert_operators.py` — convert_ifelse,
convert_while_loop, convert_logical_*). Each helper checks at runtime
whether its operands are symbolic: python values keep plain python
semantics; symbolic tensors lower to the static `cond`/`while_loop`
layers (-> lax.cond / lax.while_loop)."""
from __future__ import annotations

from ... import framework
from .program_translator import SymbolicTensor, current_ctx


def _is_sym(x):
    return isinstance(x, (SymbolicTensor, framework.Variable))


def _unwrap(x):
    if isinstance(x, SymbolicTensor):
        return x._var
    if isinstance(x, framework.Variable):
        return x
    # concrete eager Tensor captured as a constant; python scalars pass
    # through untouched (they stay python inside branch lambdas)
    from ..base import Tensor as EagerTensor

    if isinstance(x, EagerTensor) and current_ctx() is not None:
        return current_ctx().to_var(x)
    return x


def _loop_carry(x):
    """Loop-carried init value as a FRESH in-program var: constants are
    copied via `assign` so the captured const is never mutated between
    runs (loop vars are written in the body)."""
    from ...layers import tensor as static_t

    if isinstance(x, SymbolicTensor):
        return x._var
    if isinstance(x, framework.Variable):
        return x
    from ..base import Tensor as EagerTensor

    if isinstance(x, EagerTensor):
        return static_t.assign(current_ctx().to_var(x))
    return _scalar_const(x)


def _scalar_const(x):
    """Python scalar -> typed in-program constant. Ints carry as int32
    (JAX's default x64-disabled config truncates int64 anyway); values
    outside int32 range raise instead of silently wrapping."""
    from ...layers import tensor as static_t

    if isinstance(x, bool):
        return static_t.fill_constant([1], "bool", x)
    if isinstance(x, int):
        if not -2**31 <= x < 2**31:
            raise OverflowError(
                "@declarative while: python int %d carried through a "
                "symbolic loop exceeds int32 range" % x)
        return static_t.fill_constant([1], "int32", x)
    return static_t.fill_constant([1], "float32", float(x))


def _wrap(x):
    return SymbolicTensor(x) if isinstance(x, framework.Variable) else x


def _wrap_struct(x):
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap_struct(e) for e in x)
    return _wrap(x)


def _unwrap_struct(x):
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap_struct(e) for e in x)
    return _unwrap(x)


def _to_bool_var(pred):
    """Scalar bool var for cond/while (cast + reshape to ())."""
    from ...layers import nn as static_nn
    from ...layers import tensor as static_t

    v = _unwrap(pred)
    if str(v.dtype) != "bool":
        v = static_t.cast(v, "bool")
    if tuple(v.shape) not in ((), (1,)):
        v = static_nn.reduce_all(v) if hasattr(static_nn, "reduce_all") \
            else v
    return v


class _Undefined:
    """Sentinel for branch variables not yet bound before the `if`."""

    def __repr__(self):
        return "<undefined before branch>"


UNDEFINED = _Undefined()


def try_get(thunk):
    """Current value of an enclosing-scope name, or UNDEFINED when the
    name is not bound yet (it is only created inside the branch)."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return UNDEFINED


def convert_ifelse(pred, true_fn, false_fn, init_args=()):
    """`if pred:` — python branch for concrete preds, lax.cond-backed
    static cond for symbolic ones. Both branches take the pre-branch
    values of every assigned name as parameters and return them
    (the transformer guarantees matching structures)."""
    if not _is_sym(pred):
        return true_fn(*init_args) if pred else false_fn(*init_args)
    if current_ctx() is None:
        raise RuntimeError(
            "symbolic `if` outside @declarative capture")
    from ...layers import control_flow as cf

    def _coerce_out(o):
        # python scalars leaving a traced branch must carry a stable
        # dtype on BOTH sides (True in one branch, passthrough False in
        # the other): _scalar_const types bools as bool[1], ints int32
        if isinstance(o, (list, tuple)):
            return type(o)(_coerce_out(e) for e in o)
        if isinstance(o, (bool, int, float)):
            return _scalar_const(o)
        return o

    out = cf.cond(
        _to_bool_var(pred),
        lambda: _coerce_out(_unwrap_struct(true_fn(*init_args))),
        lambda: _coerce_out(_unwrap_struct(false_fn(*init_args))))
    return _wrap_struct(out)


def convert_while_loop(cond_fn, body_fn, loop_vars):
    """`while cond:` — loop-carried vars are the names the body assigns;
    symbolic condition lowers to the static while_loop layer. A plain
    python-valued loop keeps python semantics even when a body-local
    temporary is unbound before the loop (UNDEFINED only forbids the
    lax.while_loop path, which needs a typed init for every carry)."""
    pred = cond_fn(*loop_vars)
    if not _is_sym(pred):
        while pred:
            loop_vars = body_fn(*loop_vars)
            pred = cond_fn(*loop_vars)
        return loop_vars
    if any(v is UNDEFINED for v in loop_vars):
        raise NameError(
            "@declarative symbolic `while`: every loop-carried variable "
            "must be bound before the loop (the loop may run zero times)")
    if current_ctx() is None:
        raise RuntimeError(
            "symbolic `while` outside @declarative capture")
    from ...layers import control_flow as cf

    def body(*vs):
        outs = _unwrap_struct(tuple(body_fn(*_wrap_struct(tuple(vs)))))
        # a body may assign a python literal to a carried name (e.g.
        # `done = True`); coerce it like the carry init so the loop's
        # per-iteration signature stays (Variable, ...) throughout
        return tuple(o if isinstance(o, framework.Variable)
                     else _scalar_const(o) for o in outs)

    out = cf.while_loop(
        lambda *vs: _to_bool_var(cond_fn(*_wrap_struct(tuple(vs)))),
        body,
        tuple(_loop_carry(v) for v in loop_vars))
    return tuple(_wrap_struct(tuple(out)))


def _coerce_bool(y):
    """Python bool riding in a symbolic logical op -> bool constant var
    (e.g. a loop-ctl flag that is tensor in one branch, python in the
    other)."""
    if _is_sym(y):
        return y
    from ...layers import tensor as static_t

    return static_t.fill_constant([1], "bool", bool(y))


def convert_logical_and(x_fn, y_fn):
    x = x_fn()
    if not _is_sym(x):
        return y_fn() if x else x
    y = _coerce_bool(y_fn())
    from ...layers import nn as static_nn

    return _wrap(static_nn.logical_and(_unwrap(x), _unwrap(y)))


def convert_logical_or(x_fn, y_fn):
    x = x_fn()
    if not _is_sym(x):
        return x if x else y_fn()
    y = _coerce_bool(y_fn())
    from ...layers import nn as static_nn

    return _wrap(static_nn.logical_or(_unwrap(x), _unwrap(y)))


def convert_logical_not(x):
    if not _is_sym(x):
        return not x
    from ...layers import nn as static_nn

    return _wrap(static_nn.logical_not(_unwrap(x)))


def convert_len(x):
    if _is_sym(x):
        return int(_unwrap(x).shape[0])
    return len(x)


def python_only(value, construct):
    """Marks a control-flow test position that must stay python: raises
    when a tensor reaches it (e.g. `if tensor: return ...` — only
    supported shapes lower to lax.cond/while_loop)."""
    if _is_sym(value):
        raise NotImplementedError(
            "@declarative: a tensor condition reached a %s construct, "
            "which keeps python semantics — restructure so both "
            "branches are a single `return`, or assign instead of "
            "returning/breaking" % construct)
    return value


def convert_print(*args, **kwargs):
    """`print(...)` in converted code (reference: print_transformer.py):
    symbolic tensors become runtime print ops; pure-python calls keep
    builtin print."""
    if not any(_is_sym(a) for a in args):
        return print(*args, **kwargs)
    from ...layers import control_flow as cf

    for a in args:
        if _is_sym(a):
            cf.Print(_unwrap(a), message="print:")
        else:
            print(a, end=" ")


def convert_assert(cond, message=None):
    """`assert cond[, msg]` (reference: assert_transformer.py): symbolic
    conditions become a runtime assert op; python values assert now."""
    if _is_sym(cond):
        from ...layers import control_flow as cf

        cf.Assert(_to_bool_var(cond),
                  name=str(message) if message is not None else "")
        return
    assert cond, message


_CAST_DTYPES = {"int": "int32", "float": "float32", "bool": "bool"}


def convert_cast(x, kind):
    """`int(x)` / `float(x)` / `bool(x)` on tensors (reference:
    cast_transformer.py): lowers to a cast op; python values keep the
    builtin conversion."""
    if not _is_sym(x):
        return {"int": int, "float": float, "bool": bool}[kind](x)
    from ...layers import tensor as static_t

    return _wrap(static_t.cast(_unwrap(x), _CAST_DTYPES[kind]))
