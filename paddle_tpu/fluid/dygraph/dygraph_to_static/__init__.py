"""Dygraph -> static-graph translation (reference:
`python/paddle/fluid/dygraph/dygraph_to_static/` — ProgramTranslator
`program_translator.py:349`, the AST transformer suite, and the C++
`ProgramDescTracer` `imperative/jit/program_desc_tracer.h:47`).

TPU-native design: instead of a ProgramDesc tape hook inside the C++
tracer, eager ops all funnel through one python choke point
(`dygraph.base.trace_op`); capture mode redirects that choke point to
`Block.append_op`, so the dygraph network re-executes symbolically and
builds a real static `Program` (which then lowers to ONE XLA
computation, the same path Executor uses). Data-dependent `if`/`while`
are AST-rewritten onto the static `cond`/`while_loop` layers, which
lower to `lax.cond`/`lax.while_loop`.
"""
from .program_translator import (  # noqa: F401
    ProgramTranslator, StaticFunction, ConcreteProgram, SymbolicTensor,
    capture_program,
)
from .ast_transformer import convert_to_static  # noqa: F401
from . import convert_operators  # noqa: F401
