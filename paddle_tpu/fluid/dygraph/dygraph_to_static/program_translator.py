"""Capture machinery: eager network -> static Program.

Reference parity: `dygraph_to_static/program_translator.py:349`
(ProgramTranslator + per-signature ConcreteProgram cache) and
`imperative/jit/program_desc_tracer.h:47` (op capture). Here capture
reuses the static front end: each eager `trace_op` call is appended to
the default Program via `Block.append_op`, which also runs compile-time
shape inference (the reference's InferShape pass), so `x.shape` works
in user code during tracing.
"""
from __future__ import annotations

import functools
import threading
import weakref
from typing import Dict, List, Optional

import numpy as np

from ... import framework
from ....core.scope import global_scope
from ....core.types import normalize_dtype


# ---------------------------------------------------------------------------
# capture context
# ---------------------------------------------------------------------------

_state = threading.local()


def current_ctx() -> Optional["CaptureContext"]:
    return getattr(_state, "ctx", None)


class CaptureContext:
    """Maps eager tensors (parameters / captured constants) to static
    persistable vars while a capture is active."""

    def __init__(self, main: framework.Program):
        self.main = main
        self.var_map: Dict[int, framework.Variable] = {}
        self.params: List[tuple] = []  # (eager Tensor, static Variable)

    def to_var(self, t):
        """Static var for any trace_op input."""
        if isinstance(t, SymbolicTensor):
            return t._var
        if isinstance(t, framework.Variable):
            return t
        key = id(t)
        v = self.var_map.get(key)
        if v is not None:
            return v
        gb = self.main.global_block()
        trainable = getattr(t, "trainable", False) and not t.stop_gradient
        if t.persistable and trainable:
            var = gb.create_parameter(
                name=t.name, shape=list(t.shape), dtype=t.dtype,
                trainable=True)
        else:
            var = gb.create_var(
                name=t.name if t.persistable
                else framework.unique_name("capture_const"),
                shape=list(t.shape), dtype=t.dtype, persistable=True,
                stop_gradient=True)
        global_scope().set_var(var.name, t._val)
        self.var_map[key] = var
        self.params.append((t, var))
        return var

    def refresh_scope(self):
        """Re-publish current eager values (params train between calls)."""
        scope = global_scope()
        for t, var in self.params:
            scope.set_var(var.name, t._val)


def capture_trace_op(op_type, ins, attrs, out_slots):
    """The symbolic twin of dygraph trace_op: append a static op (one
    output var per declared slot) to the current block."""
    ctx = current_ctx()
    prog = framework.default_main_program()
    block = prog.current_block()
    attrs = {k: v for k, v in attrs.items() if v is not None}
    in_vars = {}
    for slot, ts in ins.items():
        vs = [ctx.to_var(t) for t in ts if t is not None]
        if vs:
            in_vars[slot] = vs
    out_vars = {}
    flat = []
    for slot in out_slots:
        ov = block.create_var(
            name=framework.unique_name("%s.%s" % (op_type, slot.lower())))
        out_vars[slot] = [ov]
        flat.append(ov)
    block.append_op(type=op_type, inputs=in_vars, outputs=out_vars,
                    attrs=attrs)
    return [SymbolicTensor(v) for v in flat]


# ---------------------------------------------------------------------------
# SymbolicTensor — dygraph Tensor interface over a static Variable
# ---------------------------------------------------------------------------

from .. import base as dy_base  # noqa: E402  (cycle-safe: late import)


class SymbolicTensor(dy_base.Tensor):
    """Stands in for an eager Tensor during capture: all the operator
    sugar on Tensor funnels through trace_op, which the capture hook
    redirects here, so user dygraph code runs unmodified."""

    def __init__(self, var):
        self._var = var
        self.name = var.name
        self.stop_gradient = var.stop_gradient
        self.persistable = var.persistable
        self.trainable = getattr(var, "trainable", True)
        self._grad = None
        self._backward_ran = False

    @property
    def shape(self):
        return tuple(self._var.shape)

    @property
    def dtype(self):
        return self._var.dtype

    @property
    def ndim(self):
        return len(self._var.shape)

    def __len__(self):
        return int(self._var.shape[0])

    def numpy(self):
        raise RuntimeError(
            "Tensor %r is symbolic (inside @declarative capture); concrete "
            "values are only available at run time" % self.name)

    item = numpy

    def __bool__(self):
        raise RuntimeError(
            "cannot convert a symbolic Tensor to bool — data-dependent "
            "python control flow must go through the @declarative AST "
            "conversion (if/while) or layers.cond/while_loop")

    def detach(self):
        t = SymbolicTensor(self._var)
        t.stop_gradient = True
        return t

    def backward(self, retain_graph=False):
        raise RuntimeError("backward() is not available on symbolic "
                           "tensors; differentiate the @declarative "
                           "function's program instead")

    def __repr__(self):
        return "SymbolicTensor(%s, shape=%s, dtype=%s)" % (
            self.name, self.shape, self.dtype)

    def __getitem__(self, idx):
        from ...layers import nn as static_nn

        if isinstance(idx, int):
            out = static_nn.slice(self._var, axes=[0], starts=[idx],
                                  ends=[idx + 1])
            out = static_nn.squeeze(out, axes=[0]) \
                if hasattr(static_nn, "squeeze") else out
            return SymbolicTensor(out)
        if isinstance(idx, slice):
            start = idx.start or 0
            stop = idx.stop if idx.stop is not None else self.shape[0]
            if idx.step not in (None, 1):
                raise NotImplementedError("strided symbolic slicing")
            return SymbolicTensor(static_nn.slice(
                self._var, axes=[0], starts=[int(start)],
                ends=[int(stop)]))
        raise NotImplementedError(
            "symbolic __getitem__ supports int and contiguous slice only")


# ---------------------------------------------------------------------------
# capture + ConcreteProgram
# ---------------------------------------------------------------------------

def _spec_of(a):
    if isinstance(a, dy_base.Tensor):
        return (tuple(a.shape), str(a.dtype))
    if isinstance(a, np.ndarray):
        return (tuple(a.shape), str(a.dtype))
    return ("pyval", repr(a))


def _is_tensor_arg(a):
    return isinstance(a, (dy_base.Tensor, np.ndarray))


class ConcreteProgram:
    """One captured (program, feeds, fetches) per input signature
    (reference: program_translator.py ConcreteProgram).

    Training support (TPU-native replacement for the reference's
    ProgramTranslator train-to-static path): when called under an
    active tracer with trainable captured parameters, the captured
    Program is lowered to a pure jax function of (params, feeds); the
    forward runs jitted and ONE tape entry with a whole-program
    custom vjp (rematerializing jax.vjp, itself jitted) is recorded,
    so `loss.backward()` delivers gradients into the eager parameter
    tensors and optimizer.minimize()/step() trains them."""

    def __init__(self, main, startup, feed_names, fetch_vars, template,
                 ctx, kw_feed_keys=()):
        self.main = main
        # feeds here are the caller's eager Tensor buffers, re-fed every
        # forward: never donate them (lowering._feed_donate opt-out).
        # The feed list also rides on the program so tpu-lint's
        # donation checker audits the dygraph-to-static path with the
        # REAL feed set (these vars are not `is_data`-marked, so the
        # checker's default feed discovery would miss them)
        main._feed_donate = False
        main._feed_names = list(feed_names)
        self.startup = startup
        self.feed_names = feed_names
        self.fetch_vars = fetch_vars
        self.template = template  # output structure
        self.ctx = ctx
        # kwarg keys that became feed vars (sorted); their feed names come
        # after the positional ones in feed_names
        self.kw_feed_keys = tuple(kw_feed_keys)
        self._exe = None
        self._pure = None       # (fn, state_mut, state_ro)
        self._diff_cache = {}   # frozenset(diff names) -> jit entry

    def _writeback(self, new_values):
        """Publish program-updated persistable state (BN running stats,
        inplace-assigned buffers) back into the captured eager tensors so
        eager<->static state stays coherent across calls."""
        for t, var in self.ctx.params:
            nv = new_values.get(var.name)
            if nv is not None:
                t._val = nv

    def run(self, tensor_args):
        tracer = framework._dygraph_tracer()
        diff_names = self._diff_names(tensor_args) \
            if tracer is not None and tracer._has_grad else []
        if diff_names:
            return self._run_diff(tensor_args, tracer, diff_names)

        from ...executor import Executor
        from ....core.scope import global_scope as _gs

        if self._exe is None:
            self._exe = Executor()
        self.ctx.refresh_scope()
        feed = {}
        for name, a in zip(self.feed_names, tensor_args):
            feed[name] = a._val if isinstance(a, dy_base.Tensor) \
                else np.asarray(a)
        outs = self._exe.run(self.main, feed=feed,
                             fetch_list=list(self.fetch_vars),
                             return_numpy=False)
        scope = _gs()
        self._writeback({var.name: scope.find_var(var.name)
                         for _, var in self.ctx.params})
        wrapped = [dy_base.wrap_raw(o) for o in outs]
        return _pack_like(self.template, wrapped)

    # -- differentiable path ----------------------------------------------
    def _diff_names(self, tensor_args):
        import jax.numpy as jnp

        def is_float(t):
            return jnp.issubdtype(t._val.dtype, jnp.inexact)

        names = [var.name for t, var in self.ctx.params
                 if getattr(t, "trainable", False)
                 and not t.stop_gradient and is_float(t)]
        for name, a in zip(self.feed_names, tensor_args):
            if isinstance(a, dy_base.Tensor) and not a.stop_gradient \
                    and is_float(a):
                names.append(name)
        return names

    def _build_pure(self):
        from ... import lowering

        block = self.main.global_block()
        fetch_names = [v.name for v in self.fetch_vars]
        state_in, state_out = lowering.analyze_block(
            block, list(self.feed_names), fetch_names)
        fn = lowering.build_block_fn(self.main, block,
                                     list(self.feed_names), fetch_names,
                                     state_in, state_out)
        sout = set(state_out)
        mut = [n for n in state_in if n in sout]
        ro = [n for n in state_in if n not in sout]
        return fn, mut, ro

    def _run_diff(self, tensor_args, tracer, diff_names):
        import jax
        import jax.numpy as jnp

        if self._pure is None:
            self._pure = self._build_pure()
        fn, mut, ro = self._pure

        values = {}
        eager_of = {}
        for t, var in self.ctx.params:
            values[var.name] = t._val
            eager_of[var.name] = t
        for name, a in zip(self.feed_names, tensor_args):
            values[name] = a._val if isinstance(a, dy_base.Tensor) \
                else dy_base.to_tensor_value(np.asarray(a))
            if isinstance(a, dy_base.Tensor):
                eager_of[name] = a
        missing = [n for n in (list(self.feed_names) + mut + ro)
                   if n not in values]
        if missing:
            from ....core.scope import global_scope as _gs

            for n in list(missing):
                v = _gs().find_var(n)
                if v is not None:
                    values[n] = v
                    missing.remove(n)
        if missing:
            raise RuntimeError(
                "@declarative training: vars %s are read by the captured "
                "program but have no captured eager value (create layers "
                "outside the declarative function)" % (missing,))

        key = frozenset(diff_names)
        entry = self._diff_cache.get(key)
        if entry is None:
            feed_names = list(self.feed_names)

            def pure(diff, nondiff, seed):
                env = dict(nondiff)
                env.update(diff)
                return fn({n: env[n] for n in feed_names},
                          {n: env[n] for n in mut},
                          {n: env[n] for n in ro}, seed)

            entry = {"pure": pure, "fwd": jax.jit(pure), "bwd": None}
            self._diff_cache[key] = entry

        diff_vals = {n: values[n] for n in diff_names}
        nondiff_vals = {n: v for n, v in values.items()
                        if n not in diff_vals}
        seed = np.uint32(tracer._seed_counter % (2**31))
        tracer._seed_counter += 1
        fetches, new_states = entry["fwd"](diff_vals, nondiff_vals, seed)
        self._writeback(new_states)

        float_idx = tuple(i for i, v in enumerate(fetches)
                          if jnp.issubdtype(v.dtype, jnp.inexact))
        if entry["bwd"] is None:
            pure = entry["pure"]

            def bwd(diff, nondiff, seed_, cts):
                def f(d):
                    fs, _ = pure(d, nondiff, seed_)
                    return [fs[i] for i in float_idx]

                _, vjp_fn = jax.vjp(f, diff)
                return vjp_fn(list(cts))[0]

            entry["bwd"] = jax.jit(bwd)
        bwd_jit = entry["bwd"]

        out_tensors = [
            dy_base.Tensor(v, stop_gradient=i not in float_idx)
            for i, v in enumerate(fetches)]
        in_tensors = [eager_of[n] for n in diff_names]

        def custom_vjp(cotangents):
            cts = [cotangents[i] for i in float_idx]
            gd = bwd_jit(diff_vals, nondiff_vals, seed, cts)
            return [gd[n] for n in diff_names]

        tracer.record(dy_base.TapeEntry(
            "concrete_program", {}, (), in_tensors, (), out_tensors,
            None, custom_vjp=custom_vjp))
        return _pack_like(self.template, out_tensors)


def _flatten_outs(x, acc):
    if isinstance(x, (list, tuple)):
        for e in x:
            _flatten_outs(e, acc)
    else:
        acc.append(x)
    return acc


def _pack_like(template, flat):
    it = iter(flat)

    def rec(t):
        if isinstance(t, (list, tuple)):
            return type(t)(rec(e) for e in t)
        return next(it)

    return rec(template)


def capture_program(fn, args, kwargs=None):
    """Trace `fn` (already AST-converted) into a fresh static Program.
    Tensor/ndarray args — positional AND keyword — become feed vars;
    everything else is baked in. (Round-1 advisory fix: tensor kwargs
    used to be captured as constants bound to the first call's value
    while still participating in the cache key, silently computing with
    stale data on later calls.)"""
    kwargs = kwargs or {}
    main = framework.Program()
    startup = framework.Program()
    ctx = CaptureContext(main)
    feed_names = []
    sym_args = []
    kw_feed_keys = []
    sym_kwargs = {}
    with framework.program_guard(main, startup):
        gb = main.global_block()

        def feed_var(a, name):
            shape = tuple(a.shape)
            dtype = a.dtype if isinstance(a, dy_base.Tensor) \
                else normalize_dtype(a.dtype)
            var = gb.create_var(name=name, shape=shape, dtype=dtype,
                                is_data=True, stop_gradient=True)
            feed_names.append(name)
            return SymbolicTensor(var)

        for i, a in enumerate(args):
            sym_args.append(feed_var(a, "declarative_in_%d" % i)
                            if _is_tensor_arg(a) else a)
        for k in sorted(kwargs):
            a = kwargs[k]
            if _is_tensor_arg(a):
                sym_kwargs[k] = feed_var(a, "declarative_kw_%s" % k)
                kw_feed_keys.append(k)
            else:
                sym_kwargs[k] = a
        prev = current_ctx()
        _state.ctx = ctx
        # leave dygraph mode: Block.append_op refuses to run under an
        # active eager tracer, and capture must not hit the eager path
        old_tracer = framework._switch_tracer(None)
        try:
            out = fn(*sym_args, **sym_kwargs)
        finally:
            framework._switch_tracer(old_tracer)
            _state.ctx = prev
    flat = _flatten_outs(out, [])
    fetch_vars = []
    for o in flat:
        if isinstance(o, SymbolicTensor):
            fetch_vars.append(o._var)
        elif isinstance(o, framework.Variable):
            fetch_vars.append(o)
        else:
            raise TypeError(
                "@declarative function returned a non-Tensor leaf %r" % (o,))
    return ConcreteProgram(main, startup, feed_names, fetch_vars, out, ctx,
                           kw_feed_keys=kw_feed_keys)


# ---------------------------------------------------------------------------
# ProgramTranslator + StaticFunction (the @declarative wrapper)
# ---------------------------------------------------------------------------

class ProgramTranslator:
    """Process-wide switch + cache owner (reference:
    program_translator.py:349; singleton via get_instance)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enable_to_static = True
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, enable_to_static=True):
        self.enable_to_static = bool(enable_to_static)

    def get_program(self, fn, *args, **kwargs):
        sf = fn if isinstance(fn, StaticFunction) else StaticFunction(fn)
        concrete = sf.concrete_program(*args, **kwargs)
        return concrete.main, concrete.startup, concrete.feed_names, \
            concrete.fetch_vars

    def get_func(self, fn):
        from .ast_transformer import convert_to_static

        return convert_to_static(fn)

    def get_output(self, fn, *args, **kwargs):
        sf = fn if isinstance(fn, StaticFunction) else StaticFunction(fn)
        return sf(*args, **kwargs)


class StaticFunction:
    """Callable produced by @declarative: per-signature capture cache;
    falls back to plain eager execution when translation is disabled."""

    def __init__(self, fn):
        functools.update_wrapper(self, fn)
        self._fn = fn
        self._converted = None
        self._cache: Dict[tuple, ConcreteProgram] = {}
        self._bound_to = None
        # per-Layer-instance caches: a ConcreteProgram pins the
        # instance's parameters, so its lifetime must follow the instance
        self._instance_caches = weakref.WeakKeyDictionary()

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        bound = StaticFunction.__new__(StaticFunction)
        bound.__dict__.update(self.__dict__)
        bound._bound_to = obj
        try:
            cache = self._instance_caches.get(obj)
            if cache is None:
                cache = {}
                self._instance_caches[obj] = cache
        except TypeError:  # unweakrefable instance: uncached per call
            cache = {}
        bound._cache = cache
        return bound

    @property
    def converted(self):
        if self._converted is None:
            from .ast_transformer import convert_to_static

            self._converted = convert_to_static(self._fn)
        return self._converted

    def _full_args(self, args):
        if self._bound_to is not None:
            return (self._bound_to,) + tuple(args)
        return tuple(args)

    def concrete_program(self, *args, **kwargs):
        # the bound instance is identified by its per-instance cache, so
        # the key covers only the call arguments
        key = tuple(_spec_of(a) for a in args) + tuple(
            sorted((k, _spec_of(v)) for k, v in kwargs.items()))
        cp = self._cache.get(key)
        if cp is None:
            cp = capture_program(self.converted, self._full_args(args),
                                 kwargs)
            self._cache[key] = cp
        return cp

    def __call__(self, *args, **kwargs):
        if current_ctx() is not None:
            # nested @declarative: inline into the enclosing capture
            return self.converted(*self._full_args(args), **kwargs)
        if not ProgramTranslator.get_instance().enable_to_static:
            return self._fn(*self._full_args(args), **kwargs)
        cp = self.concrete_program(*args, **kwargs)
        tensor_args = [a for a in self._full_args(args)
                       if _is_tensor_arg(a)]
        tensor_args += [kwargs[k] for k in cp.kw_feed_keys]
        return cp.run(tensor_args)
