"""Layer base class for dygraph (reference:
`python/paddle/fluid/dygraph/layers.py:60-700`)."""
from __future__ import annotations

import collections
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from .. import framework
from ..initializer import XavierInitializer, ConstantInitializer
from ..param_attr import ParamAttr
from . import base


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._full_name = framework.unique_name(
            name_scope or type(self).__name__.lower())
        self._dtype = dtype
        self._parameters: Dict[str, base.Tensor] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, base.Tensor] = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self.training = True

    def full_name(self):
        return self._full_name

    # -- parameters ----------------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype="float32",
                         is_bias=False, default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        init = attr.initializer or default_initializer or (
            ConstantInitializer(0.0) if is_bias else XavierInitializer())
        name = attr.name or framework.unique_name(
            self._full_name + (".b" if is_bias else ".w"))
        return base.create_eager_parameter(attr, shape, dtype, init,
                                           trainable=attr.trainable,
                                           name=name)

    def add_parameter(self, name, parameter):
        if name in self._buffers:
            raise KeyError(
                "attribute %r is already a buffer of this layer; "
                "state-dict keys are attribute paths and must be unique"
                % name)
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if name in self._parameters:
            raise KeyError(
                "attribute %r is already a parameter of this layer; "
                "state-dict keys are attribute paths and must be unique"
                % name)
        tensor.persistable = persistable
        self._buffers[name] = tensor
        return tensor

    def parameters(self, include_sublayers=True):
        out = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return out

    def named_parameters(self, prefix=""):
        for name, p in self._parameters.items():
            yield (prefix + name if not prefix else
                   prefix + "." + name), p
        for lname, l in self._sub_layers.items():
            sub_prefix = lname if not prefix else prefix + "." + lname
            yield from l.named_parameters(sub_prefix)

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for l in self._sub_layers.values():
            out.append(l)
            out.extend(l.sublayers())
        return out

    def named_sublayers(self, prefix=""):
        for name, l in self._sub_layers.items():
            p = name if not prefix else prefix + "." + name
            yield p, l
            yield from l.named_sublayers(p)

    # -- modes ---------------------------------------------------------------
    def train(self):
        self.training = True
        t = framework._dygraph_tracer()
        if t:
            t._train_mode = True
        for l in self._sub_layers.values():
            l.train()
        return self

    def eval(self):
        self.training = False
        t = framework._dygraph_tracer()
        if t:
            t._train_mode = False
        for l in self._sub_layers.values():
            l.eval()
        return self

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   prefix=""):
        """Keys are structured attribute paths ("fc.weight") so that two
        independently built instances of the same architecture agree —
        the reference derives keys the same way (dygraph/layers.py
        state_dict via hierarchy traversal)."""
        dest = destination if destination is not None else \
            collections.OrderedDict()
        for name, p in self._parameters.items():
            dest[prefix + name] = p
        for name, b in self._buffers.items():
            dest[prefix + name] = b
        if include_sublayers:
            for name, l in self._sub_layers.items():
                l.state_dict(dest, prefix=prefix + name + ".")
        return dest

    def set_dict(self, state_dict, include_sublayers=True):
        import jax.numpy as jnp

        own = self.state_dict()
        # fallback: checkpoints written before structured keys were keyed
        # by the globally-unique runtime param name
        by_pname = {t.name: key for key, t in own.items()}
        for name, t in own.items():
            v = state_dict.get(name)
            if v is None:
                continue
            arr = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
            t._assign_raw(jnp.asarray(arr))
        for name, v in state_dict.items():
            if name not in own and name in by_pname:
                arr = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
                own[by_pname[name]]._assign_raw(jnp.asarray(arr))

    load_dict = set_dict
    set_state_dict = set_dict

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # -- hooks / call --------------------------------------------------------
    def register_forward_post_hook(self, hook):
        key = len(self._forward_post_hooks)
        self._forward_post_hooks[key] = hook
        return HookRemoveHelper(self._forward_post_hooks, key)

    def register_forward_pre_hook(self, hook):
        key = len(self._forward_pre_hooks)
        self._forward_pre_hooks[key] = hook
        return HookRemoveHelper(self._forward_pre_hooks, key)

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # -- attribute magic -----------------------------------------------------
    def _purge_attr(self, name, keep=None):
        """Drop `name` from __dict__ and every registry except `keep`:
        re-binding an attribute to a different KIND (param <-> sublayer
        <-> plain value/None) must not leave a stale entry that shadows
        the new one (__getattr__ only fires when normal lookup misses)
        or pollutes parameters()/state_dict."""
        self.__dict__.pop(name, None)
        for reg in ("_sub_layers", "_parameters", "_buffers"):
            if reg == keep:
                continue
            d = self.__dict__.get(reg)
            if d is not None:
                d.pop(name, None)

    def __setattr__(self, name, value):
        if isinstance(value, base.Tensor) and value.persistable:
            params = self.__dict__.get("_parameters")
            if params is not None:
                buffers = self.__dict__.get("_buffers")
                if buffers is not None and name in buffers:
                    # re-point the existing buffer slot rather than
                    # shadowing it in _parameters: state-dict keys are
                    # attribute paths and must stay unique
                    self.__dict__.pop(name, None)
                    buffers[name] = value
                    return
                self._purge_attr(name, keep="_parameters")
                params[name] = value
                return
        if isinstance(value, Layer):
            subs = self.__dict__.get("_sub_layers")
            if subs is not None:
                self._purge_attr(name, keep="_sub_layers")
                subs[name] = value
                return
        # plain value (incl. None): a registered entry of any kind under
        # this name is replaced (reference Layer semantics)
        self._purge_attr(name)
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        params = self.__dict__.get("_parameters")
        if params and name in params:
            return params[name]
        subs = self.__dict__.get("_sub_layers")
        if subs and name in subs:
            return subs[name]
        bufs = self.__dict__.get("_buffers")
        if bufs and name in bufs:
            return bufs[name]
        raise AttributeError("%s has no attribute %r"
                             % (type(self).__name__, name))


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks = hooks
        self._key = key

    def remove(self):
        self._hooks.pop(self._key, None)


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        for i, l in enumerate(layers):
            if isinstance(l, tuple):
                self.add_sublayer(l[0], l[1])
            else:
                self.add_sublayer(str(i), l)

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x

    # reference Sequential supports len/iteration/indexing
    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def __iter__(self):
        return iter(self._sub_layers.values())

    def __len__(self):
        return len(self._sub_layers)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __iter__(self):
        return iter(self._parameters.values())

    def __len__(self):
        return len(self._parameters)

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]
