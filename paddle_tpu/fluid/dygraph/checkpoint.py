"""Dygraph save/load (reference:
`python/paddle/fluid/dygraph/checkpoint.py:33,98`)."""
from __future__ import annotations

import os
import pickle

import numpy as np


def save_dygraph(state_dict, model_path):
    """Save a state dict (param name -> Tensor) to <model_path>.pdparams."""
    d = {}
    is_opt = False
    for k, v in state_dict.items():
        if hasattr(v, "numpy"):
            d[k] = v.numpy()
        else:
            d[k] = np.asarray(v)
            is_opt = True
    suffix = ".pdopt" if is_opt else ".pdparams"
    path = model_path + suffix
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(d, f, protocol=2)


def load_dygraph(model_path):
    """Returns (param_dict, optimizer_dict)."""
    params, opt = None, None
    if os.path.exists(model_path + ".pdparams"):
        with open(model_path + ".pdparams", "rb") as f:
            params = pickle.load(f)
    if os.path.exists(model_path + ".pdopt"):
        with open(model_path + ".pdopt", "rb") as f:
            opt = pickle.load(f)
    if params is None and opt is None:
        raise ValueError("no checkpoint found at %r" % model_path)
    return params, opt
