"""fluid.unique_name — the public unique-name namespace.

Reference parity: `python/paddle/fluid/unique_name.py` (generate /
generate_with_ignorable_key / switch / guard; `fluid.unique_name.guard()`
is the idiom in virtually every reference multi-program script). The
generator state is the SAME one `framework.unique_name` /
`framework.unique_name_guard` use, so the two surfaces compose.
"""
from __future__ import annotations

import contextlib

from . import framework

UniqueNameGenerator = framework._UniqueNameGenerator


def generate(key: str) -> str:
    """Unique name with `key` as prefix, e.g. fc_0, fc_1, ..."""
    return framework.unique_name(key)


def generate_with_ignorable_key(key: str) -> str:
    """Names for intermediate vars the user never addresses.

    Intentional deviation from the reference: this version's static
    path returns `generator(key)` with NO prefix (reference
    unique_name.py:126); here the `_generated_var_` tag (the
    reference's DYGRAPH-side convention) is applied unconditionally so
    save/load and debug dumps can always recognize ignorable vars.
    Generated names therefore differ from reference static programs —
    tests/test_fluid_compat_surface.py pins the prefixed behavior."""
    return framework.unique_name("_generated_var_" + key)


def switch(new_generator=None):
    """Replace the global generator; returns the previous one."""
    old = framework._name_generator
    framework._name_generator = (new_generator if new_generator
                                 is not None else UniqueNameGenerator())
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Fresh (or given) generator within the `with` scope — keeps name
    counters of independently built programs from colliding."""
    if isinstance(new_generator, str):
        # reference accepts a string prefix here
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
