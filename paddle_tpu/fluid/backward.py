"""Autodiff: `append_backward(loss)` and `gradients(targets, inputs)`.

Reference parity: `python/paddle/fluid/backward.py:1215` walks ops in
reverse and asks each op's C++ GradOpMaker for grad OpDescs, inserting
`_grad` ops plus sum ops for multi-consumer variables. TPU-native design:
gradients are a *transform*, not a program rewrite — a single `backward`
pseudo-op records (loss, diff targets); lowering runs the forward segment
under `jax.vjp` so XLA differentiates the whole traced computation at once.
`X@GRAD` variables still appear in the block (same naming contract,
`framework.py` GRAD_SUFFIX) so optimizers, grad clip, regularizers and
tests interoperate unchanged.
"""
from __future__ import annotations

from typing import List, Optional

from . import framework
from .framework import Variable, Parameter, grad_var_name


def _collect_forward_used_names(block, upto_idx):
    used = set()
    for op in block.ops[:upto_idx]:
        used.update(op.input_arg_names)
        used.update(op.output_arg_names)
    return used


def _grad_topo_index(block, upto_idx, names):
    """For each name, the index of the LAST forward op that reads it
    (looking through control-flow sub-blocks). The vjp produces
    gradients by walking the forward in reverse, so a var with a LARGER
    last-use index gets its gradient EARLIER in the backward section —
    this is the production order the bucketed gradient collectives
    (parallel/sharded_update.plan_buckets) sort by, letting each
    bucket's reduce-scatter issue while the rest of backward computes."""
    from .lowering import _op_reads_writes

    want = set(names)
    last = {}
    for i, op in enumerate(block.ops[:upto_idx]):
        for n in _op_reads_writes(op)[0]:
            if n in want:
                last[n] = i
    return last


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Append the backward section for `loss`; returns [(param, grad)]."""
    assert isinstance(loss, Variable), "loss must be a Variable"
    block = loss.block
    program = block.program
    no_grad = set()
    if no_grad_set:
        no_grad = {v.name if isinstance(v, Variable) else v
                   for v in no_grad_set}

    upto = len(block.ops)
    used = _collect_forward_used_names(block, upto)

    if parameter_list is not None:
        params = []
        for p in parameter_list:
            v = block.var(p) if isinstance(p, str) else p
            params.append(v)
    else:
        params = [p for p in program.global_block().all_parameters()
                  if p.trainable]
    params = [p for p in params if p.name in used and p.name not in no_grad]

    # leaf inputs that ask for a gradient (OpTest check_grad feeds these)
    leaf_inputs = []
    for name in used:
        v = block._find_var_recursive(name)
        if (v is not None and not v.stop_gradient and not v.persistable
                and v.op is None and not isinstance(v, Parameter)
                and name not in no_grad):
            leaf_inputs.append(v)

    diff_vars = params + leaf_inputs
    diff_names = [v.name for v in diff_vars]

    params_grads = []
    for v in diff_vars:
        g = block.create_var(
            name=grad_var_name(v.name), shape=v.shape, dtype=v.dtype,
            persistable=False, stop_gradient=True)
        if isinstance(v, Parameter) or v in params:
            params_grads.append((v, g))

    loss_grad = block.create_var(
        name=grad_var_name(loss.name), shape=loss.shape,
        dtype=loss.dtype, stop_gradient=True)

    attrs = {
        "loss_name": loss.name,
        "diff_names": diff_names,
        "loss_scale": 1.0,
        "_is_backward": True,
        # grad production order for bucketed collectives (see
        # _grad_topo_index): larger index = grad materializes earlier
        # in the backward sweep
        "grad_topo": _grad_topo_index(block, upto, diff_names),
    }
    # recompute segments (reference backward.py:629): checkpoint names
    # recorded on the backward op; lowering splits the forward at each
    # checkpoint and wraps the segments in jax.checkpoint (remat).
    if checkpoints:
        attrs["checkpoints"] = [v.name if isinstance(v, Variable) else v
                                for v in checkpoints]
    block.append_op(
        type="backward",
        inputs={"Loss": [loss]},
        outputs={"Grad": [grad_var_name(n) for n in diff_names],
                 "LossGrad": [loss_grad]},
        attrs=attrs)
    return params_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Partial grads (reference: backward.py:1795)."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    loss = targets[0]
    block = loss.block
    diff_names = [v.name if isinstance(v, Variable) else v for v in inputs]
    grads = []
    for n in diff_names:
        v = block.var(n)
        grads.append(block.create_var(
            name=grad_var_name(n), shape=v.shape, dtype=v.dtype,
            stop_gradient=True))
    block.append_op(
        type="backward", inputs={"Loss": [loss]},
        outputs={"Grad": [g.name for g in grads]},
        attrs={"loss_name": loss.name, "diff_names": diff_names,
               "loss_scale": 1.0, "_is_backward": True,
               "grad_topo": _grad_topo_index(block, len(block.ops),
                                             diff_names)})
    return grads
