"""fluid.evaluator — the DEPRECATED pre-metrics evaluator API
(reference: `python/paddle/fluid/evaluator.py:45-299`, which warns and
points at fluid.metrics). Kept for surface parity: each class wraps the
corresponding streaming metric from `fluid.metrics` / metric ops, with
the reference's deprecation warning."""
from __future__ import annotations

import warnings

import numpy as np

from . import metrics as _metrics


def _warn(cls):
    warnings.warn(
        "The %s is deprecated, because maintain a modified program "
        "inside evaluator cause bug easily, please use "
        "fluid.metrics.%s instead." % (cls, cls), Warning)


class Evaluator:
    """Base class (reference evaluator.py:45): subclasses accumulate
    over minibatches and expose eval()/reset()."""

    def __init__(self, name, **kwargs):
        _warn(self.__class__.__name__)
        self.name = name

    def reset(self):
        raise NotImplementedError

    def eval(self, executor=None, eval_program=None):
        raise NotImplementedError


class ChunkEvaluator(Evaluator):
    """Streaming chunk F1 (reference evaluator.py:127); delegates to
    metrics.ChunkEvaluator over per-batch (num_infer, num_label,
    num_correct) chunk counts."""

    def __init__(self, input=None, label=None, chunk_scheme=None,
                 num_chunk_types=None, excluded_chunk_types=None):
        super().__init__("chunk_eval")
        self._m = _metrics.ChunkEvaluator()

    def reset(self):
        self._m.reset()

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self._m.update(num_infer_chunks, num_label_chunks,
                       num_correct_chunks)

    def eval(self, executor=None, eval_program=None):
        return self._m.eval()


class EditDistance(Evaluator):
    """Streaming mean edit distance (reference evaluator.py:218)."""

    def __init__(self, input=None, label=None, ignored_tokens=None):
        super().__init__("edit_distance")
        self._m = _metrics.EditDistance()

    def reset(self):
        self._m.reset()

    def update(self, distances, seq_num):
        self._m.update(distances, seq_num)

    def eval(self, executor=None, eval_program=None):
        return self._m.eval()


class DetectionMAP(Evaluator):
    """Streaming detection mAP (reference evaluator.py:299): feed each
    batch's detections + ground truth; eval() runs the registered
    `detection_map` op in accumulative mode."""

    def __init__(self, input=None, gt_label=None, gt_box=None,
                 gt_difficult=None, class_num=None,
                 background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        super().__init__("detection_map")
        self.class_num = class_num
        self.background_label = background_label
        self.overlap_threshold = overlap_threshold
        self.evaluate_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        self._state = None

    def update(self, detect_res, detect_lod, label, label_lod):
        """One batch: detections [[label, score, x1,y1,x2,y2]...] with
        lod offsets, labels [[label, x1,y1,x2,y2, difficult]...].
        Runs the registered `detection_map` op INCREMENTALLY, threading
        its Accum* state (reference detection_map_op.h accumulative
        mode) — eval() is then O(1) and no batch is retained."""
        from ..ops.registry import get_op

        op = get_op("detection_map")
        ins = {"DetectRes": [np.asarray(detect_res, np.float32)],
               "DetectResLod": [np.asarray(detect_lod, np.int64)],
               "Label": [np.asarray(label, np.float32)],
               "LabelLod": [np.asarray(label_lod, np.int64)]}
        if self._state is not None:
            s = self._state
            # op outputs are raw arrays (not slot lists): pass them
            # whole — indexing [0] here would slice off the first row
            # of each state tensor and silently drop prior batches
            ins.update({
                "HasState": [np.asarray([1], np.int32)],
                "PosCount": [s["AccumPosCount"]],
                "TruePos": [s["AccumTruePos"]],
                "TruePosLod": [s["AccumTruePosLod"]],
                "FalsePos": [s["AccumFalsePos"]],
                "FalsePosLod": [s["AccumFalsePosLod"]],
            })
        self._state = op.compute(ins, {
            "class_num": self.class_num,
            "background_label": self.background_label,
            "overlap_threshold": self.overlap_threshold,
            "evaluate_difficult": self.evaluate_difficult,
            "ap_type": self.ap_version})

    def eval(self, executor=None, eval_program=None):
        if self._state is None:
            raise ValueError("no batches fed to DetectionMAP")
        return float(np.asarray(self._state["MAP"]).reshape(-1)[0])
