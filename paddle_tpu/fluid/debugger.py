"""fluid.debugger — program pretty-printing + graphviz DOT export
(reference: `python/paddle/fluid/debugger.py:112-285`: colored
pseudo-code listing of a ProgramDesc and draw_block_graphviz). Works on
this framework's Program/Block/Operator objects; the DOT writer is pure
text (no graphviz binding needed to produce the .dot file)."""
from __future__ import annotations


def repr_var(var):
    shape = tuple(getattr(var, "shape", ()) or ())
    return "%s[%s]%s" % (getattr(var, "dtype", "?"),
                         ",".join(str(d) for d in shape),
                         " persist" if getattr(var, "persistable", False)
                         else "")


def repr_attr(name, value):
    if isinstance(value, str):
        return '%s="%s"' % (name, value)
    return "%s=%s" % (name, value)


def repr_op(op):
    """One op as pseudo-code: outs = op_type(ins, attrs)."""
    outs = ", ".join("%s=%s" % (k, list(v))
                     for k, v in sorted(op.output_names.items()) if v)
    ins = ", ".join("%s=%s" % (k, list(v))
                    for k, v in sorted(op.input_names.items()) if v)
    attrs = ", ".join(repr_attr(k, v)
                      for k, v in sorted(op.attrs.items())
                      if not k.startswith("op_"))
    return "%s = %s(%s)%s" % (outs or "()", op.type, ins,
                              " {%s}" % attrs if attrs else "")


def pprint_block_codes(block, show_backward=False):
    lines = ["block {"]
    for name, var in sorted(block.vars.items()):
        if not show_backward and name.endswith("@GRAD"):
            continue
        lines.append("  var %s : %s" % (name, repr_var(var)))
    for op in block.ops:
        if not show_backward and op.type.endswith("_grad"):
            continue
        lines.append("  " + repr_op(op))
    lines.append("}")
    return "\n".join(lines)


def pprint_program_codes(program, show_backward=False):
    """The whole program as pseudo-code text (reference
    debugger.py:112)."""
    return "\n".join(pprint_block_codes(program.block(i), show_backward)
                     for i in range(program.num_blocks))


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write the block's op/var dataflow as a graphviz DOT file
    (reference debugger.py:229). Vars are ellipses, ops are boxes;
    `highlights` names are filled red."""
    highlights = set(highlights or [])

    def esc(s):
        return s.replace('"', r'\"')

    lines = ["digraph G {", "  rankdir=TB;"]
    seen_vars = set()
    for name in block.vars:
        seen_vars.add(name)
        style = ' style=filled fillcolor="red"' \
            if name in highlights else ""
        lines.append('  "v_%s" [label="%s" shape=ellipse%s];'
                     % (esc(name), esc(name), style))
    for i, op in enumerate(block.ops):
        lines.append('  "op_%d" [label="%s" shape=box '
                     'style=filled fillcolor="lightgrey"];'
                     % (i, esc(op.type)))
        for n in op.input_arg_names:
            if n in seen_vars:
                lines.append('  "v_%s" -> "op_%d";' % (esc(n), i))
        for n in op.output_arg_names:
            if n in seen_vars:
                lines.append('  "op_%d" -> "v_%s";' % (i, esc(n)))
    lines.append("}")
    text = "\n".join(lines) + "\n"
    with open(path, "w") as f:
        f.write(text)
    return path
