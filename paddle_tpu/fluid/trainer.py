"""Dataset-training entry points (reference: `Executor::RunFromDataset`
`framework/executor.cc:170`, MultiTrainer/HogwildWorker loops
`framework/hogwild_worker.cc`, double-buffered reader
`operators/reader/buffered_reader.cc`).

TPU-native: the per-thread Hogwild op loop is replaced by ONE compiled
train step; throughput comes from overlap, not host threads racing on a
shared scope:
- a feeder thread parses/prepares batches into a bounded queue while the
  device computes (the reference's DataFeed channel);
- steps run with device-resident results (no per-step host sync) — jax's
  async dispatch queues step N+1's transfer while step N executes, so
  feeding, H2D copy and compute pipeline like the reference's
  double-buffered reader. Fetched values materialize on host only every
  `print_period` steps and at the end.
"""
from __future__ import annotations

import queue
import sys
import threading

import numpy as np

_SENTINEL = object()


def train_from_dataset(executor, program, dataset, scope=None,
                       fetch_list=None, print_period=100,
                       queue_size=4, checkpoint_dir=None,
                       checkpoint_every_n_steps=0, checkpoint_num=3):
    """When checkpoint_dir is set, the latest checkpoint under it is
    restored before training (auto-resume after preemption) and all
    persistables + TrainStatus are saved asynchronously every
    checkpoint_every_n_steps steps and at the end (fluid/checkpoint.py;
    reference: fleet collective save_checkpoint/load_checkpoint,
    incubate/fleet/collective/__init__.py:236-341)."""
    if dataset is None:
        raise ValueError("dataset is required")
    from . import framework

    program = program or framework.default_main_program()

    ckpt = None
    start_step = 0
    if checkpoint_dir:
        from . import checkpoint as ckpt_mod

        status = ckpt_mod.load_checkpoint(executor, checkpoint_dir,
                                          program, scope=scope)
        if status is not None:
            start_step = max(status.step_no, 0)
        ckpt = ckpt_mod.AsyncCheckpointer(
            checkpoint_dir, program, checkpoint_num=checkpoint_num,
            scope=scope)

    q: "queue.Queue" = queue.Queue(maxsize=max(int(queue_size), 1))
    feeder_err = []
    stop = threading.Event()

    def _feeder():
        try:
            for feed in dataset._iter_batches():
                while not stop.is_set():
                    try:
                        q.put(feed, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 - surface in main thread
            feeder_err.append(e)
        finally:
            # the sentinel must not be dropped on a full queue (the
            # consumer would hang at end-of-dataset); retry like the
            # data puts, bailing only when the consumer said stop
            while True:
                try:
                    q.put(_SENTINEL, timeout=0.2)
                    break
                except queue.Full:
                    if stop.is_set():
                        break

    t = threading.Thread(target=_feeder, daemon=True,
                         name="paddle_tpu-data-feeder")
    t.start()

    it = 0
    results = None
    try:
        while True:
            feed = q.get()
            if feed is _SENTINEL:
                break
            it += 1
            if it <= start_step:
                continue  # already-trained steps of a resumed run
            # return_numpy=False keeps results device-resident: no host
            # sync per step, so the feeder and the next H2D overlap this
            # compute
            results = executor.run(program, feed=feed,
                                   fetch_list=fetch_list, scope=scope,
                                   return_numpy=False)
            if print_period and fetch_list and it % print_period == 0:
                vals = [np.asarray(v) for v in results]
                print("step %d: %s" % (it, [float(np.ravel(v)[0])
                                            for v in vals]))
            if (ckpt is not None and checkpoint_every_n_steps
                    and it % checkpoint_every_n_steps == 0):
                ckpt.save_async(ckpt_mod.TrainStatus(epoch_no=0,
                                                     step_no=it))
    finally:
        # signal the feeder to stop (don't drain the whole dataset just
        # to surface a step error) and unblock any pending put
        stop.set()
        try:
            q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=5.0)
        if ckpt is not None:
            # only publish a final checkpoint when NEW steps ran: a
            # resumed run over a shorter dataset must not regress the
            # latest step_no below what the weights already contain
            if it > start_step:
                ckpt.save_async(ckpt_mod.TrainStatus(epoch_no=0,
                                                     step_no=it))
            # always flush + surface background write errors, even when
            # a step raised — the pending snapshot is the freshest state
            # (but never let a checkpoint IO error mask the step error)
            step_error_in_flight = sys.exc_info()[0] is not None
            try:
                ckpt.close()
            except Exception:  # noqa: BLE001
                if not step_error_in_flight:
                    raise
    if feeder_err:
        raise feeder_err[0]
    if results is not None:
        return [np.asarray(v) for v in results]
    return None
