"""Dataset-training entry points (reference: `Executor::RunFromDataset`
`framework/executor.cc:170`, MultiTrainer/HogwildWorker loops
`framework/hogwild_worker.cc`).

TPU-native: the per-thread Hogwild op loop is replaced by iterating the
dataset's batch stream through the same compiled train step; XLA pipelines
host feeding against device compute.
"""
from __future__ import annotations


def train_from_dataset(executor, program, dataset, scope=None,
                       fetch_list=None, print_period=100):
    if dataset is None:
        raise ValueError("dataset is required")
    from . import framework

    program = program or framework.default_main_program()
    it = 0
    results = None
    for feed in dataset._iter_batches():
        results = executor.run(program, feed=feed,
                               fetch_list=fetch_list, scope=scope)
        it += 1
    return results
