"""Dataset-training entry points (reference: `Executor::RunFromDataset`
`framework/executor.cc:170`, MultiTrainer/HogwildWorker loops
`framework/hogwild_worker.cc`, double-buffered reader
`operators/reader/buffered_reader.cc`).

TPU-native: the per-thread Hogwild op loop is replaced by ONE compiled
train step; throughput comes from overlap, not host threads racing on a
shared scope:
- the device prefetcher (reader/prefetcher.py) parses/prepares batches
  AND issues their non-blocking H2D transfers on a background thread
  while the device computes, `FLAGS_tpu_prefetch_depth` batches deep —
  batch N+1 is already in HBM (sharded against the program's mesh for
  data-parallel programs) when step N retires, so `Executor.run`'s
  on-device fast path never re-puts it (the reference's
  double-buffered reader, extended past the host channel);
- steps run with device-resident results (no per-step host sync) — jax's
  async dispatch keeps the queue full. Fetched values materialize on
  host only every `print_period` steps and at the end.
"""
from __future__ import annotations

import sys

import numpy as np


def train_from_dataset(executor, program, dataset, scope=None,
                       fetch_list=None, print_period=100,
                       queue_size=4, checkpoint_dir=None,
                       checkpoint_every_n_steps=0, checkpoint_num=3):
    """When checkpoint_dir is set, the latest checkpoint under it is
    restored before training (auto-resume after preemption) and all
    persistables + TrainStatus are saved asynchronously every
    checkpoint_every_n_steps steps and at the end (fluid/checkpoint.py;
    reference: fleet collective save_checkpoint/load_checkpoint,
    incubate/fleet/collective/__init__.py:236-341)."""
    if dataset is None:
        raise ValueError("dataset is required")
    from . import framework

    program = program or framework.default_main_program()

    ckpt = None
    start_step = 0
    if checkpoint_dir:
        from . import checkpoint as ckpt_mod

        status = ckpt_mod.load_checkpoint(executor, checkpoint_dir,
                                          program, scope=scope)
        if status is not None:
            start_step = max(status.step_no, 0)
        ckpt = ckpt_mod.AsyncCheckpointer(
            checkpoint_dir, program, checkpoint_num=checkpoint_num,
            scope=scope)

    from ..reader.prefetcher import prefetch_to_device

    # the prefetcher replaces the old host-only feeder queue: same
    # bounded-depth background thread, but batches leave it already ON
    # DEVICE (sharded for data-parallel programs), so the H2D DMA for
    # batch N+1 rides under step N's compute. Already-trained steps of
    # a resumed run are skipped HOST-side, before the prefetcher —
    # paying an H2D transfer per discarded batch would be pure waste
    import itertools

    batches = dataset._iter_batches()
    if start_step:
        batches = itertools.islice(batches, start_step, None)
    depth = max(int(queue_size), 1)
    pf = prefetch_to_device(batches, size=depth,
                            sharding=executor.feed_sharding(program))

    it = start_step
    results = None
    try:
        for feed in pf:
            it += 1
            # return_numpy=False keeps results device-resident: no host
            # sync per step, so the feeder and the next H2D overlap this
            # compute
            results = executor.run(program, feed=feed,
                                   fetch_list=fetch_list, scope=scope,
                                   return_numpy=False)
            if print_period and fetch_list and it % print_period == 0:
                vals = [np.asarray(v) for v in results]
                print("step %d: %s" % (it, [float(np.ravel(v)[0])
                                            for v in vals]))
            if (ckpt is not None and checkpoint_every_n_steps
                    and it % checkpoint_every_n_steps == 0):
                ckpt.save_async(ckpt_mod.TrainStatus(epoch_no=0,
                                                     step_no=it))
    finally:
        # stop the producer + drain in-flight device buffers (don't run
        # the whole dataset just to surface a step error)
        pf.close()
        if ckpt is not None:
            # only publish a final checkpoint when NEW steps ran: a
            # resumed run over a shorter dataset must not regress the
            # latest step_no below what the weights already contain
            if it > start_step:
                ckpt.save_async(ckpt_mod.TrainStatus(epoch_no=0,
                                                     step_no=it))
            # always flush + surface background write errors, even when
            # a step raised — the pending snapshot is the freshest state
            # (but never let a checkpoint IO error mask the step error)
            step_error_in_flight = sys.exc_info()[0] is not None
            try:
                ckpt.close()
            except Exception:  # noqa: BLE001
                if not step_error_in_flight:
                    raise
    if results is not None:
        return [np.asarray(v) for v in results]
    return None
