"""2.0-era input helpers (reference: `python/paddle/fluid/input.py`):
`fluid.one_hot` and `fluid.embedding` — the v2 kernels with the newer
shape contract (no trailing-1 dimension games; both append their new
axis to the id tensor's own shape)."""
from __future__ import annotations

from .layer_helper import LayerHelper
from .layers.nn import _single


def one_hot(input, depth, allow_out_of_range=False):
    """Append a depth axis to `input`'s shape (reference input.py:24:
    [N_1,...,N_k] -> [N_1,...,N_k, depth]) — the one_hot_v2 kernel;
    layers.one_hot keeps the fluid-1.x trailing-1 contract instead.

    Deviation: with allow_out_of_range=False the reference raises on an
    out-of-range id; a data-dependent raise is impossible inside an XLA
    program, so out-of-range ids produce all-zero rows in both modes
    (the allow_out_of_range=True behavior)."""
    return _single("one_hot_v2", {"X": [input]},
                   {"depth": depth,
                    "allow_out_of_range": bool(allow_out_of_range)},
                   dtype="float32")


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """Embedding lookup appending emb_size to the id tensor's shape
    (reference input.py:130, the lookup_table_v2 kernel — unlike
    fluid.layers.embedding's v1 op, a trailing [..., 1] ids axis is
    KEPT: ids [N, 1] -> out [N, 1, emb])."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(helper.param_attr, shape=list(size),
                                dtype=dtype)
    pad = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    return _single("lookup_table_v2", {"W": [w], "Ids": [input]},
                   {"padding_idx": pad, "is_sparse": is_sparse,
                    "is_distributed": is_distributed},
                   dtype=dtype, helper=helper)
