"""LayerHelper: shared plumbing for layers.* builders (reference:
`python/paddle/fluid/layer_helper.py`). Creates parameters (appending their
init op to the startup program), intermediate output vars, and dispatches
append_op; in dygraph mode ops execute eagerly through the tracer."""
from __future__ import annotations

from . import framework
from .framework import Variable, unique_name, in_dygraph_mode
from .initializer import (
    ConstantInitializer, XavierInitializer, _global_weight_initializer,
    _global_bias_initializer,
)
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name else unique_name(layer_type)

    @property
    def main_program(self):
        return framework.default_main_program()

    @property
    def startup_program(self):
        return framework.default_startup_program()

    @property
    def main_block(self):
        return self.main_program.current_block()

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [attr] + [ParamAttr(**{
                k: v for k, v in attr.__dict__.items() if k != "name"})
                for _ in range(length - 1)]
        return attr

    # -- creation ----------------------------------------------------------
    def create_parameter(self, attr, shape, dtype="float32", is_bias=False,
                         default_initializer=None, stop_gradient=False):
        if attr is False:
            return None
        attr = ParamAttr._to_attr(attr)
        if attr.name is None:
            attr.name = unique_name(".".join([self.name, "w" if not is_bias
                                              else "b"]))
        init = attr.initializer or default_initializer or (
            _global_bias_initializer() if is_bias
            else _global_weight_initializer())

        if in_dygraph_mode():
            from .dygraph import base as dy_base

            return dy_base.create_eager_parameter(
                attr, shape, dtype, init, trainable=attr.trainable)

        main_block = self.main_program.global_block()
        param = main_block.create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs())
        # mirror var in startup program + init op there
        startup_block = self.startup_program.global_block()
        s_param = startup_block.create_var(
            name=param.name, shape=shape, dtype=dtype, persistable=True)
        init(s_param, startup_block)
        return param

    def create_variable_for_type_inference(self, dtype="float32",
                                           stop_gradient=False):
        return self.main_block.create_var(
            name=unique_name(".".join([self.name, "tmp"])),
            dtype=dtype, shape=(), stop_gradient=stop_gradient)

    def create_variable(self, **kwargs):
        return self.main_block.create_var(**kwargs)

    def create_global_variable(self, persistable=True, **kwargs):
        return self.main_program.global_block().create_var(
            persistable=persistable, **kwargs)

    def set_variable_initializer(self, var, initializer):
        startup_block = self.startup_program.global_block()
        s_var = startup_block.create_var(
            name=var.name, shape=var.shape, dtype=var.dtype,
            persistable=True)
        initializer(s_var, startup_block)

    # -- op dispatch -------------------------------------------------------
    def append_op(self, **kwargs):
        return self.main_block.append_op(**kwargs)

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.bias_attr
        if bias_attr is False or bias_attr is None:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        if in_dygraph_mode():
            from .dygraph import base as dy_base

            return dy_base.trace_op(
                "elementwise_add", {"X": [input_var], "Y": [b]},
                {"axis": dim_start}, ["Out"])[0]
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [out]},
            attrs={"axis": dim_start})
        return out

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        if in_dygraph_mode():
            from .dygraph import base as dy_base

            return dy_base.trace_op(act_type, {"X": [input_var]}, act,
                                    ["Out"])[0]
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [out]}, attrs=act)
        return out

    def input(self, name="Input"):
        v = self.kwargs.get(name.lower(), self.kwargs.get("input"))
        return v

    def input_dtype(self, name="input"):
        v = self.kwargs.get(name)
        if isinstance(v, (list, tuple)):
            v = v[0]
        return v.dtype


def apply_op(helper_or_type, op_type, inputs, attrs, out_slots,
             out_dtype=None):
    """Mode-polymorphic op application used by functional layers.

    out_slots: list of output slot names (each one var) or dict slot->count.
    Returns list of output vars/tensors in slot order.
    """
    if in_dygraph_mode():
        from .dygraph import base as dy_base

        slots = (list(out_slots) if not isinstance(out_slots, dict)
                 else out_slots)
        return dy_base.trace_op(op_type, inputs, attrs, slots)

    helper = (helper_or_type if isinstance(helper_or_type, LayerHelper)
              else LayerHelper(op_type))
    outs = {}
    flat = []
    if isinstance(out_slots, dict):
        for slot, n in out_slots.items():
            vs = [helper.create_variable_for_type_inference(
                out_dtype or "float32") for _ in range(n)]
            outs[slot] = vs
            flat.extend(vs)
    else:
        for slot in out_slots:
            v = helper.create_variable_for_type_inference(
                out_dtype or "float32")
            outs[slot] = [v]
            flat.append(v)
    helper.append_op(type=op_type, inputs=inputs, outputs=outs, attrs=attrs)
    return flat
